"""On-device sampling and the fully-jitted decode loop.

The reference samples on the host between every token (reference:
src/apps/dllama/dllama.cpp:45-59), which on TPU costs a host↔device round
trip per token — behind a remote-tunnel PJRT connection that round trip is
dozens of ms, an order of magnitude more than the forward pass itself. Here
the whole decode loop (forward → sample → feed back) runs under one
``lax.scan`` on device; the host dispatches once and fetches N tokens.

Sampling is FUSED into the scan (ISSUE 13): temperature / top-k / top-p
filtering and the categorical draw run per step on device, drawing coins
from the counter-mode PRNG in :mod:`distributed_llama_tpu.prng`. The coin
for the token drawn after consuming stream position ``p`` is a pure
function of ``(request seed, p)`` — no sampler state exists, so:

* a stream is bit-identical however the decode is chunked into dispatches;
* PR 8/9's preemption-requeue and failover replays re-draw the exact coins
  on any replica without shipping sampler state (positions are defined by
  token content, not replica state);
* the host ``Sampler``'s counter mode (tokenizer.py) replays the same
  draws from fetched logits — the xorshift host-parity verification mode.

Candidate semantics (shared with the host counter sampler, and the
contract the parity suite asserts): candidates are ordered by descending
temperature-scaled logit (ties broken by lower token id — ``lax.top_k``
order); top-k keeps the first k; top-p keeps the nucleus prefix
(token ``i`` stays while the mass strictly before it is < topp, the
reference's inclusive-crossing rule, src/tokenizer.cpp:334-369); the draw
is inverse-CDF over the kept prefix with one uniform coin. With both
filters off the draw is inverse-CDF in vocab order (no sort — the
multinomial path). All float math is f32. Host parity on the filtered
paths rests on the f32 softmax (max-subtract, exp, full-vocab sum,
divide) and the ≤``TOPP_FAST_K``-element kept-prefix cumsum reducing
identically in numpy and XLA — measured exact on the CPU backend over
thousands of draws, though a denominator or boundary value landing
within 1 ulp of a coin/topp crossing can in principle flip a pick on
another backend. The full-vocab cumsum paths (the multinomial draw and
nuclei wider than the fast-path window) carry the larger version of the
same caveat: XLA's parallel prefix sum may associate differently from a
sequential host cumsum.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_llama_tpu import prng
from distributed_llama_tpu.engine import integrity
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig

# width of the sorted-candidate fast path: when the kept prefix (top-k ∧
# nucleus) provably fits in the largest TOPP_FAST_K candidates (virtually
# always for topp <= 0.95 on a trained model), the pick runs on one top_k
# instead of a full-vocab sort; a lax.cond falls back to the full sort
# otherwise, so the result is EXACT either way
TOPP_FAST_K = 128

# vocab floor for the partition-based bare-top-p fallback: the bit-space
# binary searches add ~400 ops to the decode program (a few seconds of XLA
# compile per decode shape) and only beat the full sort where the sort is
# actually expensive — production-width vocabularies (53× at V=32k,
# BENCH_KERNELS_r07.json). Below the floor the routing — and therefore the
# compiled program — is byte-identical to the pre-partition one: tiny test
# models must not pay compile time for a path that would LOSE to their
# cheap sort (a fresh multi-second compile mid-serving is exactly what the
# preemption race tests schedule against).
TOPP_PARTITION_MIN_V = 4096


def _keep_count(vals, cum, topp, topk):
    """Kept-prefix width over descending candidates [rows, K]: the
    inclusive-crossing nucleus count (keep candidate i while the mass
    strictly before it < topp) ∧ top-k, clipped to [1, K]. THE keep rule
    of the host/device/spec parity contract — one definition shared by
    the categorical pick and the speculative filtered distribution
    (tokenizer.Sampler._sample_counter mirrors it in numpy)."""
    K = vals.shape[-1]
    topp = jnp.broadcast_to(jnp.asarray(topp, jnp.float32), vals.shape[:-1])
    topk = jnp.broadcast_to(jnp.asarray(topk, jnp.int32), vals.shape[:-1])
    topp_act = (topp > 0.0) & (topp < 1.0)
    n_nuc = jnp.where(
        topp_act, jnp.sum(cum - vals < topp[..., None], axis=-1), K
    )
    n_k = jnp.where(topk > 0, jnp.minimum(topk, K), K)
    return jnp.clip(jnp.minimum(n_nuc, n_k), 1, K)


def _pick_sorted(vals, idxs, coin, topp, topk):
    """Inverse-CDF pick over descending candidates.

    ``vals`` [B, K] candidate probabilities in canonical order (descending
    scaled logit, ties by lower id), ``idxs`` [B, K] their token ids,
    ``coin`` [B] uniforms, ``topp``/``topk`` [B] runtime filters. Keeps
    the prefix ``min(top-k, nucleus)`` (:func:`_keep_count`) and draws
    ``r = coin * kept_mass``; the pick is the first candidate whose
    cumulative mass exceeds ``r`` — exactly the host counter sampler's
    arithmetic, value for value."""
    K = vals.shape[-1]
    cum = jnp.cumsum(vals, axis=-1)
    n_keep = _keep_count(vals, cum, topp, topk)
    total = jnp.take_along_axis(cum, (n_keep - 1)[:, None], axis=-1)[:, 0]
    r = coin * total
    below = jnp.sum(
        (jnp.arange(K)[None, :] < n_keep[:, None]) & (cum <= r[:, None]),
        axis=-1,
    )
    pick = jnp.minimum(below, n_keep - 1)
    return jnp.take_along_axis(idxs, pick[:, None], axis=-1)[:, 0]


def _desc_key(scaled: jax.Array) -> jax.Array:
    """uint32 key monotone INCREASING in the f32 ``scaled`` logit (the
    classic sign-flip bit trick), so value-threshold searches can walk key
    bits instead of sorting: for non-negative floats the IEEE bits are
    already ordered; negative floats order reversed, so flip all their
    bits and set the sign bit on the rest."""
    b = jax.lax.bitcast_convert_type(scaled.astype(jnp.float32), jnp.uint32)
    return jnp.where(b >> 31 == 1, ~b, b | jnp.uint32(0x80000000))


def _topp_partition_pick(probs, scaled, coin, topp):
    """EXACT bare-top-p pick by partition (threshold) selection — no
    full-vocab sort anywhere (the ROADMAP item 2 follow-up: near-flat
    untrained-model-shaped logits overflow the ``TOPP_FAST_K`` window on
    every step, and the old fallback paid a full-vocab ``top_k``).

    Two 32-step binary searches over the f32 bit-space of the scaled
    logits (each step one masked full-vocab sum — O(V log V_bits) adds vs
    the sort's O(V log V) compare-exchanges, and no [V]-wide data
    movement), both phrased against the canonical candidate order
    (descending scaled logit, ties by lower id — `_keep_count`'s order):

    1. the nucleus boundary VALUE: the largest key ``v`` whose at-or-above
       mass still reaches ``topp`` (elements strictly above ``v`` are all
       kept; ties AT ``v`` keep the id-ascending prefix while the mass
       strictly before each stays < topp — the inclusive-crossing rule);
    2. the PICK value for ``r = coin × kept_mass``: the largest key whose
       strictly-above mass is ≤ r < at-or-above mass; the id-ascending
       cumsum over the (rare) ties at that value resolves the pick, and
       the result clamps to the last kept candidate exactly like
       `_pick_sorted`'s saturating count.

    Parity scope: identical to the full-sort `_pick_sorted` whenever no
    cumulative mass lands within an ulp of a coin/topp crossing — the
    masked sums here and the sorted prefix cumsum associate differently,
    the same (documented) caveat the multinomial path carries.
    Parity-tested against the sort path in tests/test_sampling.py."""
    V = probs.shape[-1]
    keys = _desc_key(scaled)

    def mass_geq(v):
        """Σ probs over candidates with key ≥ v (strictly-above plus ties)."""
        return jnp.sum(jnp.where(keys >= v[:, None], probs, 0.0), axis=-1)

    def bit_search(pred):
        """Per-row largest uint32 v with pred(v) True (pred monotone
        decreasing in v; pred(0) is True by construction)."""
        v = jnp.zeros(probs.shape[0], jnp.uint32)
        for k in range(31, -1, -1):
            cand = v | jnp.uint32(1 << k)
            v = jnp.where(pred(cand), cand, v)
        return v

    def succ(v):
        """v + 1 saturating at the uint32 max (a wrap to 0 would turn
        "strictly above the top key" into "everything")."""
        return jnp.where(v == jnp.uint32(0xFFFFFFFF), v, v + 1)

    topp = jnp.asarray(topp, jnp.float32)
    # 1. boundary value: largest v with mass(key >= v) >= topp. mass_geq is
    # a right-continuous step function constant between achieved key
    # values, so v_b always LANDS on an achieved key — its tie set is
    # non-empty, and (by maximality) the strictly-above mass is < topp, so
    # the FIRST boundary tie is always kept: the kept prefix and the clamp
    # target below are well defined with no empty-set cases.
    v_b = bit_search(lambda v: mass_geq(v) >= topp)
    above_b = mass_geq(succ(v_b))  # mass strictly above the boundary value
    # ties at the boundary keep while (strictly-before mass) < topp; the
    # id-order cumsum runs over the tie set only (rare — one key value)
    tie_b = jnp.where(keys == v_b[:, None], probs, 0.0)
    tiecum_b = jnp.cumsum(tie_b, axis=-1)
    tie_kept = (keys == v_b[:, None]) & (
        above_b[:, None] + (tiecum_b - tie_b) < topp[:, None]
    )
    kept_tie_mass = jnp.max(jnp.where(tie_kept, tiecum_b, 0.0), axis=-1)
    total = above_b + kept_tie_mass  # the kept prefix's mass
    strictly_above = keys > v_b[:, None]
    # the clamp target = the LAST kept candidate in canonical order: the
    # highest-cumsum kept boundary tie (argmax returns the first of equal
    # cumsums — only reachable through zero-probability ties, which carry
    # no mass either way)
    last_kept = jnp.argmax(
        jnp.where(tie_kept, tiecum_b, -1.0), axis=-1
    ).astype(jnp.int32)

    # 2. the draw: first candidate whose cumulative mass exceeds r
    r = coin * total
    v_p = bit_search(lambda v: mass_geq(v) > r)
    above_p = mass_geq(succ(v_p))
    tie_p = jnp.where(keys == v_p[:, None], probs, 0.0)
    tiecum_p = jnp.cumsum(tie_p, axis=-1)
    hit = (keys == v_p[:, None]) & (above_p[:, None] + tiecum_p > r[:, None])
    found = jnp.any(hit, axis=-1)
    pick = jnp.argmax(hit, axis=-1).astype(jnp.int32)  # first True = lowest id
    pick = jnp.where(found, pick, last_kept)
    # the pick must stay inside the kept prefix (r == total edge): kept
    # means strictly above the boundary, or a kept boundary tie
    in_kept = jnp.take_along_axis(
        strictly_above | tie_kept, pick[:, None], axis=-1
    )[:, 0]
    return jnp.where(in_kept, pick, last_kept)


def fused_pick(probs, scaled, coin, topp, topk, cand=None):
    """The filtered categorical pick on probabilities [B, V] (f32).

    ``scaled`` are the temperature-scaled logits the canonical candidate
    order sorts by (softmax is weakly monotone in f32, so sorting by
    ``scaled`` and reading ``probs`` values keeps host and device on the
    identical candidate sequence). ``cand`` [B, K] optionally supplies the
    candidate ids already reduced over a sharded vocab
    (:func:`sharded_topk_indices` — the tp composition); the full-vocab
    sort fallback still runs on ``probs``/``scaled`` when the kept prefix
    cannot be proven to fit. Rows with both filters inactive draw
    inverse-CDF in vocab order (no sort)."""
    B, V = probs.shape
    K = min(TOPP_FAST_K, V)
    topp_act = (topp > 0.0) & (topp < 1.0)
    topk_act = (topk > 0) & (topk < V)
    filt = topp_act | topk_act

    # multinomial (no filter): vocab-order inverse CDF over the full mass.
    # Behind a cond: the full-vocab cumsum only runs when some row actually
    # has both filters off (never, in the filtered serving default)
    def mult(_):
        cdf = jnp.cumsum(probs, axis=-1)
        r_m = coin * cdf[:, -1]
        return jnp.minimum(
            jnp.sum(cdf <= r_m[:, None], axis=-1), V - 1
        ).astype(jnp.int32)

    idx_m = jax.lax.cond(
        jnp.any(~filt), mult, lambda _: jnp.zeros((B,), jnp.int32), None
    )

    def from_full(_):
        fv, fi = jax.lax.top_k(scaled, V)
        return _pick_sorted(
            jnp.take_along_axis(probs, fi, axis=-1), fi, coin, topp, topk
        )

    if cand is not None:
        idxs = cand
        vals = jnp.take_along_axis(probs, idxs, axis=-1)
    elif K == V:
        fi = jax.lax.top_k(scaled, V)[1]
        idxs, vals = fi, jnp.take_along_axis(probs, fi, axis=-1)
    else:
        idxs = jax.lax.top_k(scaled, K)[1]
        vals = jnp.take_along_axis(probs, idxs, axis=-1)
    if cand is None and K == V:
        tok_f = _pick_sorted(vals, idxs, coin, topp, topk)
    else:
        # the fast window is exact unless a row's kept prefix could extend
        # past it. An overflowing NUCLEUS alone does not force the full
        # sort when an in-window top-k also binds: the nucleus count is
        # then provably > window >= topk, so min(nucleus, topk) = topk and
        # the window has every kept candidate (_pick_sorted's counting
        # saturates at the window, which is exactly right). A BARE top-p
        # whose nucleus overflows (near-flat, untrained-model-shaped
        # logits) takes the exact partition-based selection — no
        # full-vocab sort; only a top-k wider than the window still needs
        # the full order.
        Kw = vals.shape[-1]
        cum_k = jnp.cumsum(vals, axis=-1)
        nucleus_unfit = topp_act & (cum_k[:, -1] < topp)
        wide_topk = topk_act & (topk > Kw)
        narrow_topk = topk_act & (topk <= Kw)
        if V >= TOPP_PARTITION_MIN_V:
            need_part = nucleus_unfit & ~topk_act
            need_sort = wide_topk & (nucleus_unfit | ~topp_act)
        else:
            # small vocab: the sort is cheaper than the partition searches
            # — keep the pre-partition routing (and the identical program)
            need_part = None
            need_sort = (nucleus_unfit & ~narrow_topk) | (~topp_act & wide_topk)
        tok_f = jax.lax.cond(
            jnp.any(need_sort),
            from_full,
            lambda _: _pick_sorted(vals, idxs, coin, topp, topk),
            None,
        )
        if need_part is not None:
            tok_p = jax.lax.cond(
                jnp.any(need_part),
                lambda _: _topp_partition_pick(probs, scaled, coin, topp),
                lambda _: jnp.zeros((B,), jnp.int32),
                None,
            )
            tok_f = jnp.where(need_part, tok_p, tok_f)
    return jnp.where(filt, tok_f, idx_m)


def fused_sample_batched(
    logits,  # [B, vocab]
    seeds,  # uint32 [B] (prng.fold_seed on the host)
    pos,  # int32 [B] — position of the token each row just consumed
    temperature,  # [B]
    topp,  # [B]
    topk,  # int32 [B] (0 = off)
    draw: int = prng.DRAW_SAMPLE,
    cand=None,
) -> jax.Array:
    """Fused temperature/top-k/top-p sampling with the counter PRNG:
    one coin per row keyed ``(seed, pos, draw)``, greedy rows
    (``temperature == 0``) take the exact raw-logits argmax — bit-identical
    to a pure-greedy dispatch, coins never consumed."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    probs = jax.nn.softmax(scaled, axis=-1)
    coin = prng.device_coin(seeds, pos, draw)
    tok = fused_pick(probs, scaled, coin, topp, topk, cand=cand)
    return jnp.where(temperature == 0.0, greedy, tok.astype(jnp.int32))


def sample_token(
    logits, seed, pos, temperature, topp, topk=0
) -> jax.Array:
    """Sample one token id from f32 logits [vocab] with the fused sampler.

    ``temperature``/``topp``/``topk`` may be Python scalars (static under
    jit — a greedy call specializes to a bare argmax) or traced values
    (one compiled program serves every request's sampler settings).
    ``seed`` is the folded uint32 word; ``pos`` the consumed position the
    coin is keyed on."""
    static = not any(
        isinstance(v, jax.Array) for v in (temperature, topp, topk)
    )
    if static and temperature == 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    return fused_sample_batched(
        logits[None],
        jnp.asarray(seed, jnp.uint32)[None],
        jnp.asarray(pos, jnp.int32)[None],
        jnp.asarray(temperature, jnp.float32)[None],
        jnp.asarray(topp, jnp.float32)[None],
        jnp.asarray(topk, jnp.int32)[None],
    )[0]


def sharded_topk_indices(local_logits, axis_name, k: int):
    """Global top-k token ids composed over a vocab-sharded logits head:
    per-shard ``top_k`` on the LOCAL slice, ONE [B, k]-candidate
    all-gather, and a merge ``top_k`` — the full-vocab sort never runs,
    and only k·tp candidate words ride the collective instead of the
    whole vocabulary. Exactly equal to ``top_k`` over the gathered vocab:
    selection commutes with concatenation, and ties resolve to the lower
    global id on both (shard-major gather order == global id order)."""
    B, vs = local_logits.shape
    kl = min(k, vs)
    lv, li = jax.lax.top_k(local_logits, kl)
    gi = li + jax.lax.axis_index(axis_name) * vs
    av = jax.lax.all_gather(lv, axis_name, axis=1, tiled=True)  # [B, tp*kl]
    ai = jax.lax.all_gather(gi, axis_name, axis=1, tiled=True)
    mi = jax.lax.top_k(av, min(k, av.shape[1]))[1]
    return jnp.take_along_axis(ai, mi, axis=1)


def decode_scan(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,  # int32 scalar
    cache: jax.Array,
    pos: jax.Array,  # int32 scalar: position of first_token
    seed: jax.Array,  # uint32 scalar (prng.fold_seed on the host)
    n_steps: int,
    temperature,
    topp,
    topk=0,
    axis_name: str | None = None,
):
    """The un-jitted decode scan body: forward → fused sample → feed back.
    Returns (tokens [n_steps], cache). Coins are keyed on the absolute
    position each step consumes, so the token stream is independent of how
    the decode is chunked into dispatches — no sampler state threads
    between calls.

    With ``axis_name`` set it is the per-shard SPMD body for a shard_map'd
    tensor-parallel decode: the forward psums ride the mesh, a
    vocab-sharded logits head is all-gathered, and sampling runs
    identically on every shard (same counter → same token everywhere).
    """

    def step(carry, _):
        token, cache, p = carry
        logits, cache = llama.forward_tokens(
            cfg, params, token[None], cache, p, axis_name=axis_name
        )
        if axis_name is not None and logits.shape[-1] != cfg.vocab_size:
            logits = jax.lax.all_gather(logits, axis_name, axis=1, tiled=True)
        nxt = sample_token(logits[0], seed, p, temperature, topp, topk)
        return (nxt, cache, p + 1), nxt

    (_, cache, _), tokens = jax.lax.scan(
        step,
        (first_token.astype(jnp.int32), cache, pos.astype(jnp.int32)),
        None,
        length=n_steps,
    )
    return tokens, cache


@functools.partial(
    jax.jit, static_argnums=(0, 6, 7, 8, 9), donate_argnums=(3,)
)
def _decode_loop_jit(
    cfg, params, first_token, cache, pos, seed, n_steps, temperature, topp, topk
):
    return decode_scan(
        cfg, params, first_token, cache, pos, seed, n_steps, temperature,
        topp, topk,
    )


def decode_loop(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,  # int32 scalar
    cache: jax.Array,
    pos: jax.Array,  # int32 scalar: position of first_token
    n_steps: int,
    temperature: float,
    topp: float,
    seed: int = 0,
    topk: int = 0,
):
    """Generate ``n_steps`` tokens autoregressively on device (single chip).

    Returns (tokens [n_steps] int32, final cache). tokens[i] is the token
    sampled after consuming the token at position pos+i. Sampler settings
    are static here (the greedy program specializes to a bare argmax);
    the chunked serving path uses :func:`decode_chunk` instead.
    """
    tokens, cache = _decode_loop_jit(
        cfg, params, jnp.asarray(first_token), cache, jnp.asarray(pos),
        jnp.uint32(prng.fold_seed(seed)), int(n_steps), float(temperature),
        float(topp), int(topk),
    )
    return tokens, cache


def batched_decode_scan(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,  # int32 [B]
    cache,  # slab cache (llama.init_batch_cache)
    pos: jax.Array,  # int32 [B] per-row positions of first_tokens
    active: jax.Array,  # bool [B]
    seeds: jax.Array,  # uint32 [B] per-row folded request seeds
    n_steps: int,
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    topk: jax.Array,  # int32 [B]
    axis_name: str | None = None,
    paged=None,  # (pool, tables, matched) — zero-copy prefix aliasing
    fingerprint: bool = True,
):
    """The batched decode body: B sequences step together, each weight
    matrix read once per step. Per row it is the same forward → split →
    sample → feed-back chain as :func:`decode_scan` with the SAME
    position-keyed coins, so a row's token stream is identical to the
    single-stream chunked decode for the same request seed. Inactive rows
    compute garbage (masked out of cache writes and position advances) so
    requests can join/leave between chunks without a recompile. Returns
    (tokens [n_steps, B], cache, fingerprints uint32 [B], finite bool
    [B]) — NOTHING else needs to cross the host per chunk: the sampler is
    stateless, so no advanced keys return and no full-vocab logits are
    ever fetched. ``paged``: each row's matched prompt prefix is read from
    the shared page pool through its page table instead of the slab (the
    pool rides the scan as a read-only closure capture — no copy, no
    donation).

    Under a vocab-sharded tp head the candidate top-k is composed over the
    shards (:func:`sharded_topk_indices`) before the logits all-gather
    that the fingerprint fold needs.

    ``fingerprint`` folds each step's per-row logit argmax + token into an
    FNV-1a hash and a finiteness flag ON DEVICE (engine/integrity.py —
    the SDC detection substrate, ISSUE 10); the sampling itself is
    untouched, so the token stream is bit-identical either way.
    ``fingerprint=False`` skips the fold (same outputs, initial-state
    hashes) — the overhead-bound test compiles both and compares."""

    def step(carry, _):
        tokens, cache_c, p, h, okf = carry
        logits, cache_c = llama.forward_step_batched(
            cfg, params, tokens, cache_c, p, active, axis_name=axis_name,
            paged=paged,
        )
        cand = None
        if axis_name is not None and logits.shape[-1] != cfg.vocab_size:
            # the tp top-k composition: candidates reduce over the sharded
            # vocab BEFORE the full gather (selection by raw logits —
            # temperature scaling is order-preserving)
            cand = sharded_topk_indices(
                logits, axis_name, min(TOPP_FAST_K, cfg.vocab_size)
            )
            logits = jax.lax.all_gather(logits, axis_name, axis=1, tiled=True)
        nxt = fused_sample_batched(
            logits, seeds, p, temperature, topp, topk, cand=cand
        )
        if fingerprint:
            h, okf = integrity.fingerprint_fold(h, okf, logits, nxt)
        p2 = jnp.where(active, p + 1, p)
        return (nxt.astype(jnp.int32), cache_c, p2, h, okf), nxt

    h0, ok0 = integrity.fingerprint_init(first_tokens.shape[0])
    (_, cache, _, h, okf), tokens = jax.lax.scan(
        step,
        (
            first_tokens.astype(jnp.int32), cache, pos.astype(jnp.int32),
            h0, ok0,
        ),
        None,
        length=n_steps,
    )
    return tokens, cache, h, okf


@functools.partial(jax.jit, static_argnums=(0, 6), donate_argnums=(3,))
def decode_chunk_batched(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,
    cache,
    pos: jax.Array,
    active: jax.Array,
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    topk: jax.Array,
    seeds: jax.Array,
):
    """One chunk of the batched multi-stream decode (single chip): like
    :func:`decode_chunk` but over B concurrent sequences with per-row
    positions, sampler settings and seeds — one compiled program per
    (bucket, chunk) shape serves every mix of requests. The slab cache is
    donated and aliases in place; no sampler state returns — the next
    chunk re-keys its coins from (seed, position).

    Returns ``(out, cache)`` where ``out`` is the packed [n_steps + 2, B]
    int32 bundle of tokens + per-row logit fingerprint + finiteness flag
    (engine/integrity.py ``split_chunk_outputs``) — one fetch still moves
    everything the scheduler needs, and those int32 rows are the ONLY
    bytes that cross the host per chunk."""
    tokens, cache, h, okf = batched_decode_scan(
        cfg, params, first_tokens, cache, pos, active, seeds, n_steps,
        temperature, topp, topk,
    )
    return integrity.pack_chunk_outputs(tokens, h, okf), cache


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=(3,))
def decode_chunk_batched_paged(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,
    cache,
    pos: jax.Array,
    active: jax.Array,
    pool,  # per-layer (keys, values) page-pool halves — READ-ONLY
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    topk: jax.Array,
    seeds: jax.Array,
    tables: jax.Array,  # int32 [B, n_table] per-row page tables
    matched: jax.Array,  # int32 [B] aliased prefix lengths (0 = no alias)
):
    """:func:`decode_chunk_batched` with zero-copy prefix aliasing: rows
    whose prompt hit the radix cache read their matched prefix straight out
    of the shared page pool every step — no gathered slab duplicate exists.
    Only the slab is donated; the pool is shared across every row and
    dispatch, so it must never alias. Same packed [n_steps + 2, B] return
    bundle as :func:`decode_chunk_batched`."""
    tokens, cache, h, okf = batched_decode_scan(
        cfg, params, first_tokens, cache, pos, active, seeds, n_steps,
        temperature, topp, topk, paged=(pool, tables, matched),
    )
    return integrity.pack_chunk_outputs(tokens, h, okf), cache


# ---------------------------------------------------------------------------
# Self-speculative decoding (prompt-lookup drafts, Leviathan et al. verify):
# the host proposes up to k draft tokens from the request's own prompt +
# output n-grams (engine/speculative.py — no draft model), one verify
# forward scores [prev, d_1..d_k] in a single weight read, and the
# accept/reject below runs ON DEVICE so only (n_emit, tokens) — a handful
# of int32s — cross the host boundary per step.
# ---------------------------------------------------------------------------


def _filtered_dist(logits, temperature, topp, topk):
    """The renormalized filtered distribution p [T, vocab] the spec
    accept/redraw draws from: the SAME candidate semantics as the fused
    sampler (descending scaled-logit order, top-k ∧ nucleus prefix),
    expressed as a mask + renormalize so per-token acceptance
    probabilities exist. Returns (p, greedy_targets)."""
    T, vocab = logits.shape
    logits = logits.astype(jnp.float32)
    greedy_targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    probs = jax.vmap(jax.nn.softmax)(scaled)
    sv_i = jax.lax.top_k(scaled, vocab)[1]  # [T, V] descending order
    pv = jnp.take_along_axis(probs, sv_i, axis=-1)
    cum = jnp.cumsum(pv, axis=-1)
    n_keep = _keep_count(pv, cum, topp, topk)

    def row_rank(order_row):
        return (
            jnp.zeros((vocab,), jnp.int32)
            .at[order_row]
            .set(jnp.arange(vocab, dtype=jnp.int32))
        )

    ranks = jax.vmap(row_rank)(sv_i)
    keep = ranks < n_keep[:, None]
    filt = jnp.where(keep, probs, 0.0)
    p = filt / jnp.sum(filt, axis=-1, keepdims=True)
    return p, greedy_targets


def _cdf_pick(p, coin):
    """Vocab-order inverse-CDF draw from per-row distributions ``p``
    [T, vocab] with per-row coins [T] (mass renormalized by the row
    total, so zeroed entries never draw)."""
    vocab = p.shape[-1]
    cdf = jnp.cumsum(p, axis=-1)
    r = coin * cdf[:, -1]
    return jnp.minimum(jnp.sum(cdf <= r[:, None], axis=-1), vocab - 1).astype(
        jnp.int32
    )


def _spec_accept_row(logits, draft, draft_len, seed, pos, temperature, topp, topk):
    """Accept/reject one row's draft against its verify logits.

    ``logits``: [T, vocab] f32 (T = k + 1) — ``logits[i]`` is the model's
    next-token distribution after consuming feed position ``i`` (absolute
    stream position ``pos + i``); ``draft``: [k] int32 (entries at or
    beyond ``draft_len`` are pad). Returns ``(n_emit, tokens [T])`` where
    ``tokens[:n_emit]`` are the emitted tokens — ``n_emit - 1`` accepted
    drafts plus one correction/bonus token drawn from the model's own
    distribution.

    Greedy (temperature == 0): longest-matching-prefix against the argmax
    targets — every emitted token IS the plain decode's argmax at its
    position, so the stream is bit-identical to non-speculative decode.

    Sampled: Leviathan-style rejection sampling on counter coins. The
    prompt-lookup draft distribution is the point mass q = δ(draft_i), so
    position i accepts with probability p_i(draft_i) against the coin
    keyed ``(seed, pos + i, DRAW_SPEC_ACCEPT)`` (p = the renormalized
    top-k/top-p-filtered softmax — exactly what the fused sampler draws
    from) and a rejection redraws from the residual norm(max(p - q, 0)) =
    p with draft_i removed on the ``DRAW_SPEC_REDRAW`` coin of the emit
    position; acceptance never biases the output distribution, and the
    whole step consumes no sampler state — a replay re-keys every coin."""
    T, vocab = logits.shape
    k = T - 1
    p, greedy_targets = _filtered_dist(logits, temperature, topp, topk)

    steps = pos + jnp.arange(T, dtype=jnp.int32)
    u = prng.device_coin(
        jnp.broadcast_to(seed, (T,)), steps, prng.DRAW_SPEC_ACCEPT
    )
    redraw = prng.device_coin(
        jnp.broadcast_to(seed, (T,)), steps, prng.DRAW_SPEC_REDRAW
    )

    i_idx = jnp.arange(k)
    in_draft = i_idx < draft_len
    p_draft = p[i_idx, draft]  # [k] acceptance probability per position
    sampled_ok = u[:k] < p_draft if k else jnp.zeros((0,), bool)
    greedy_ok = draft == greedy_targets[:k]
    ok = jnp.where(temperature == 0.0, greedy_ok, sampled_ok) & in_draft
    acc = jnp.cumprod(ok.astype(jnp.int32)) if k else jnp.zeros((0,), jnp.int32)
    n_acc = jnp.sum(acc)  # accepted draft prefix length

    # one inverse-CDF draw per position (T is small): the residual draw
    # for a rejection at i < draft_len, the full draw for the bonus
    # position — both on the emit position's redraw coin
    if k:
        q = jnp.where(
            jnp.arange(vocab)[None, :] == draft[:, None], 0.0, p[:k]
        )
        resid = _cdf_pick(q, redraw[:k])
    else:
        resid = jnp.zeros((0,), jnp.int32)
    full = _cdf_pick(p, redraw)
    resid_padded = jnp.concatenate([resid, jnp.zeros((1,), jnp.int32)])
    rejected = n_acc < draft_len
    corr_sampled = jnp.where(rejected, resid_padded[n_acc], full[n_acc])
    corr = jnp.where(temperature == 0.0, greedy_targets[n_acc], corr_sampled)

    t_idx = jnp.arange(T)
    draft_padded = jnp.concatenate([draft, jnp.zeros((1,), jnp.int32)])
    tokens = jnp.where(t_idx < n_acc, draft_padded, 0)
    tokens = jnp.where(t_idx == n_acc, corr, tokens).astype(jnp.int32)
    return (n_acc + 1).astype(jnp.int32), tokens


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_step(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [T] — [prev, draft_1..draft_k] (pad beyond draft_len)
    cache,
    pos: jax.Array,  # int32 scalar: position of feed[0]
    draft_len: jax.Array,  # int32 scalar
    temperature: jax.Array,
    topp: jax.Array,
    topk: jax.Array,
    seed: jax.Array,  # uint32 scalar
):
    """One single-stream speculative step: verify forward (the ordinary
    multi-token decode at a position offset — ONE weight read for draft +
    bonus positions) fused with the on-device accept/reject. Returns
    ``(out, cache)`` with ``out = [n_emit, tokens...]`` int32 [T+1] —
    the only bytes that visit the host. Cache slots past the accepted
    prefix hold rejected-draft K/V: stale but unreachable (the next step
    writes at the advanced position before any query can see them — the
    same overshoot contract as the chunked decode's rollback)."""
    logits, cache = llama.forward_tokens(cfg, params, feed, cache, pos)
    n_emit, tokens = _spec_accept_row(
        logits, feed[1:], draft_len, seed, pos, temperature, topp, topk
    )
    return jnp.concatenate([n_emit[None], tokens]), cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_chunk_batched(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [B, T] per-row [prev, drafts...] windows
    cache,
    pos: jax.Array,  # int32 [B]
    active: jax.Array,  # bool [B]
    draft_len: jax.Array,  # int32 [B]
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    topk: jax.Array,  # int32 [B]
    seeds: jax.Array,  # uint32 [B]
):
    """One batched speculative step: every joined row's verify window rides
    ONE weight read (llama.forward_verify_batched) and the per-row
    accept/reject runs on device. Returns ``(out [B, T+1], cache)`` with
    ``out[b] = [n_emit_b, tokens_b...]`` — rows advance a VARIABLE number
    of positions per step (the scheduler applies each row's n_emit at
    fetch time). Inactive rows compute garbage into dropped cache slots,
    exactly like the plain batched chunk."""
    logits, cache = llama.forward_verify_batched(
        cfg, params, feed, cache, pos, active
    )
    n_emit, tokens = jax.vmap(_spec_accept_row)(
        logits, feed[:, 1:], draft_len, seeds, pos, temperature, topp, topk
    )
    return jnp.concatenate([n_emit[:, None], tokens], axis=1), cache


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_chunk_batched_paged(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [B, T] per-row [prev, drafts...] windows
    cache,
    pos: jax.Array,  # int32 [B]
    active: jax.Array,  # bool [B]
    pool,  # per-layer (keys, values) page-pool halves — READ-ONLY
    draft_len: jax.Array,  # int32 [B]
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    topk: jax.Array,  # int32 [B]
    seeds: jax.Array,  # uint32 [B]
    tables: jax.Array,  # int32 [B, n_table]
    matched: jax.Array,  # int32 [B]
):
    """:func:`spec_verify_chunk_batched` with zero-copy prefix aliasing:
    verify windows attend over pool pages for the matched prefix and the
    slab row for the private suffix, bit-identical to the copied-prefix
    verify (the spec × prefix-cache parity contract). The paged verify
    attention rides the fused Pallas kernel
    (``ops.attention.fused_paged_verify_attention`` — one program per
    layer instead of the segmented-scan chain) under the same
    ``DLT_FUSED_PAGED`` gate and bit-parity pins as the decode hit path."""
    logits, cache = llama.forward_verify_batched(
        cfg, params, feed, cache, pos, active, paged=(pool, tables, matched)
    )
    n_emit, tokens = jax.vmap(_spec_accept_row)(
        logits, feed[:, 1:], draft_len, seeds, pos, temperature, topp, topk
    )
    return jnp.concatenate([n_emit[:, None], tokens], axis=1), cache


@functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(3,))
def decode_chunk(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,
    cache: jax.Array,
    pos: jax.Array,
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    topk: jax.Array,
    seed: jax.Array,  # uint32 scalar
):
    """One chunk of the user-facing streaming decode (single chip): like
    :func:`decode_loop` but temperature/topp/topk are *traced* scalars —
    one compiled program per chunk size serves every request's sampler
    settings — and coins re-key per position, so the stream continues
    across chunks exactly as a single dispatch would with no state
    returned."""
    return decode_scan(
        cfg, params, first_token, cache, pos, seed, n_steps, temperature,
        topp, topk,
    )
