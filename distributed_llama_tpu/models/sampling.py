"""On-device sampling and the fully-jitted decode loop.

The reference samples on the host between every token (reference:
src/apps/dllama/dllama.cpp:45-59), which on TPU costs a host↔device round
trip per token — behind a remote-tunnel PJRT connection that round trip is
dozens of ms, an order of magnitude more than the forward pass itself. Here
the whole decode loop (forward → sample → feed back) runs under one
``lax.scan`` on device; the host dispatches once and fetches N tokens.

Semantics match the host Sampler (greedy argmax / temperature softmax /
top-p nucleus — reference: src/tokenizer.cpp:294-415) except the RNG:
jax.random replaces the xorshift generator, so seeded runs are reproducible
within this runtime but not bit-identical to the reference's draw sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_llama_tpu.engine import integrity
from distributed_llama_tpu.models import llama
from distributed_llama_tpu.models.config import LlamaConfig


def sample_token(
    logits: jax.Array, key: jax.Array, temperature, topp
) -> jax.Array:
    """Sample one token id from f32 logits [vocab].

    ``temperature``/``topp`` may be Python floats (static under jit — the
    greedy/top-p branches specialize away) or traced scalars (the chunked
    decode path, where one compiled program serves every request's sampler
    settings)."""
    if isinstance(temperature, jax.Array) or isinstance(topp, jax.Array):
        return _sample_token_dynamic(logits, key, temperature, topp)
    if temperature == 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    logits = logits / temperature
    if 0.0 < topp < 1.0:
        probs = jax.nn.softmax(logits)
        threshold = _topp_threshold(probs, topp)
        logits = jnp.where(probs >= threshold, logits, -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)


# top-k width of the nucleus-threshold fast path: when the top-p mass sits
# inside the largest TOPP_FAST_K probabilities (virtually always for
# topp <= 0.95 on a trained model), the threshold comes from one top_k
# instead of a full-vocab sort; a lax.cond falls back to the sort otherwise,
# so the result is EXACT either way
TOPP_FAST_K = 128


def _topp_threshold(probs: jax.Array, topp: jax.Array) -> jax.Array:
    """The smallest probability inside the top-p nucleus (inclusive of the
    crossing element, like the reference's last_idx logic,
    src/tokenizer.cpp:334-369). Exact: the top-k fast path is used only
    when the nucleus provably fits in the top k (prefix mass at rank i is
    monotone, so no index >= k can be counted once cum[k-1] >= topp)."""
    k = min(TOPP_FAST_K, probs.shape[-1])
    top_vals, _ = jax.lax.top_k(probs, k)
    cum_k = jnp.cumsum(top_vals)

    def fast(_):
        cutoff = jnp.sum(cum_k - top_vals < topp)
        return top_vals[jnp.maximum(cutoff - 1, 0)]

    def full(_):
        sorted_probs = jnp.sort(probs)[::-1]
        cum = jnp.cumsum(sorted_probs)
        cutoff = jnp.sum(cum - sorted_probs < topp)
        return sorted_probs[jnp.maximum(cutoff - 1, 0)]

    if k == probs.shape[-1]:
        return fast(None)
    return jax.lax.cond(cum_k[-1] >= topp, fast, full, None)


def _sample_token_dynamic(
    logits: jax.Array, key: jax.Array, temperature: jax.Array, topp: jax.Array
) -> jax.Array:
    """Same semantics with runtime-valued temperature/topp: the greedy and
    top-p decisions become ``jnp.where`` selects. Draw-identical to the static
    path for the same key (the filtered-logit construction matches — the
    fast-path threshold equals the full-sort threshold exactly), so chunked
    and single-dispatch decode produce the same stream per seed."""
    scaled = logits / jnp.maximum(temperature, 1e-6)
    probs = jax.nn.softmax(scaled)
    threshold = _topp_threshold(probs, topp)
    use_topp = (topp > 0.0) & (topp < 1.0)
    filtered = jnp.where(use_topp & (probs < threshold), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, filtered).astype(jnp.int32)
    greedy = jnp.argmax(logits).astype(jnp.int32)
    return jnp.where(temperature == 0.0, greedy, sampled)


def decode_scan(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,  # int32 scalar
    cache: jax.Array,
    pos: jax.Array,  # int32 scalar: position of first_token
    key: jax.Array,
    n_steps: int,
    temperature: float,
    topp: float,
    axis_name: str | None = None,
):
    """The un-jitted decode scan body: forward → sample → feed back.
    Returns (tokens [n_steps], cache, advanced key) — threading the returned
    key into the next call makes the token stream independent of how the
    decode is chunked into dispatches.

    With ``axis_name`` set it is the per-shard SPMD body for a shard_map'd
    tensor-parallel decode: the forward psums ride the mesh, a vocab-sharded
    logits head is all-gathered, and sampling runs identically on every
    shard (same key → same token everywhere).
    """

    def step(carry, _):
        token, cache, p, k = carry
        logits, cache = llama.forward_tokens(
            cfg, params, token[None], cache, p, axis_name=axis_name
        )
        if axis_name is not None and logits.shape[-1] != cfg.vocab_size:
            logits = jax.lax.all_gather(logits, axis_name, axis=1, tiled=True)
        k, sub = jax.random.split(k)
        nxt = sample_token(logits[0], sub, temperature, topp)
        return (nxt, cache, p + 1, k), nxt

    (_, cache, _, key), tokens = jax.lax.scan(
        step, (first_token.astype(jnp.int32), cache, pos.astype(jnp.int32), key), None,
        length=n_steps,
    )
    return tokens, cache, key


@functools.partial(
    jax.jit, static_argnums=(0, 5, 6, 7), donate_argnums=(3,)
)
def decode_loop(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,  # int32 scalar
    cache: jax.Array,
    pos: jax.Array,  # int32 scalar: position of first_token
    n_steps: int,
    temperature: float,
    topp: float,
    key: jax.Array | None = None,
):
    """Generate ``n_steps`` tokens autoregressively on device (single chip).

    Returns (tokens [n_steps] int32, final cache). tokens[i] is the token
    sampled after consuming the token at position pos+i.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    tokens, cache, _ = decode_scan(
        cfg, params, first_token, cache, pos, key, n_steps, temperature, topp
    )
    return tokens, cache


def sample_tokens_batched(
    logits: jax.Array,  # [B, vocab] f32
    keys: jax.Array,  # [B, 2] per-row PRNG keys
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
) -> jax.Array:
    """Per-row sampling with per-row keys/settings: a vmap of the dynamic
    single-row sampler, so row ``b`` draws EXACTLY what a single-stream
    chunk with the same key would (vmap preserves per-row semantics — the
    bit-parity contract of the batched decode)."""
    return jax.vmap(_sample_token_dynamic)(logits, keys, temperature, topp)


def batched_decode_scan(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,  # int32 [B]
    cache,  # slab cache (llama.init_batch_cache)
    pos: jax.Array,  # int32 [B] per-row positions of first_tokens
    active: jax.Array,  # bool [B]
    keys: jax.Array,  # [B, 2] per-row PRNG keys
    n_steps: int,
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    axis_name: str | None = None,
    paged=None,  # (pool, tables, matched) — zero-copy prefix aliasing
    fingerprint: bool = True,
):
    """The batched decode body: B sequences step together, each weight
    matrix read once per step. Per row it is the same forward → split →
    sample → feed-back chain as :func:`decode_scan`, with the SAME
    key-splitting order, so a row's token stream is identical to the
    single-stream chunked decode for the same per-row key. Inactive rows
    compute garbage (masked out of cache writes and position advances) so
    requests can join/leave between chunks without a recompile. Returns
    (tokens [n_steps, B], cache, advanced keys [B, 2], fingerprints
    uint32 [B], finite bool [B]). ``paged``: each row's matched prompt
    prefix is read from the shared page pool through its page table
    instead of the slab (the pool rides the scan as a read-only closure
    capture — no copy, no donation).

    ``fingerprint`` folds each step's per-row logit sum + token into an
    FNV-1a hash and a finiteness flag ON DEVICE (engine/integrity.py —
    the SDC detection substrate, ISSUE 10); the sampling itself is
    untouched, so the token stream is bit-identical either way.
    ``fingerprint=False`` skips the fold (same outputs, initial-state
    hashes) — the overhead-bound test compiles both and compares."""

    def step(carry, _):
        tokens, cache_c, p, ks, h, okf = carry
        logits, cache_c = llama.forward_step_batched(
            cfg, params, tokens, cache_c, p, active, axis_name=axis_name,
            paged=paged,
        )
        if axis_name is not None and logits.shape[-1] != cfg.vocab_size:
            logits = jax.lax.all_gather(logits, axis_name, axis=1, tiled=True)
        split = jax.vmap(jax.random.split)(ks)  # [B, 2, 2]
        ks2, subs = split[:, 0], split[:, 1]
        nxt = sample_tokens_batched(logits, subs, temperature, topp)
        if fingerprint:
            h, okf = integrity.fingerprint_fold(h, okf, logits, nxt)
        p2 = jnp.where(active, p + 1, p)
        return (nxt.astype(jnp.int32), cache_c, p2, ks2, h, okf), nxt

    h0, ok0 = integrity.fingerprint_init(first_tokens.shape[0])
    (_, cache, _, keys, h, okf), tokens = jax.lax.scan(
        step,
        (
            first_tokens.astype(jnp.int32), cache, pos.astype(jnp.int32),
            keys, h0, ok0,
        ),
        None,
        length=n_steps,
    )
    return tokens, cache, keys, h, okf


@functools.partial(jax.jit, static_argnums=(0, 6), donate_argnums=(3,))
def decode_chunk_batched(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,
    cache,
    pos: jax.Array,
    active: jax.Array,
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    keys: jax.Array,
):
    """One chunk of the batched multi-stream decode (single chip): like
    :func:`decode_chunk` but over B concurrent sequences with per-row
    positions, sampler settings and PRNG keys — one compiled program per
    (bucket, chunk) shape serves every mix of requests. The slab cache is
    donated and aliases in place; advanced per-row keys return so each
    stream continues exactly as its single-stream chunked decode would.

    Returns ``(out, cache, keys)`` where ``out`` is the packed
    [n_steps + 2, B] int32 bundle of tokens + per-row logit fingerprint +
    finiteness flag (engine/integrity.py ``split_chunk_outputs``) — one
    fetch still moves everything the scheduler needs."""
    tokens, cache, keys, h, okf = batched_decode_scan(
        cfg, params, first_tokens, cache, pos, active, keys, n_steps,
        temperature, topp,
    )
    return integrity.pack_chunk_outputs(tokens, h, okf), cache, keys


@functools.partial(jax.jit, static_argnums=(0, 7), donate_argnums=(3,))
def decode_chunk_batched_paged(
    cfg: LlamaConfig,
    params,
    first_tokens: jax.Array,
    cache,
    pos: jax.Array,
    active: jax.Array,
    pool,  # per-layer (keys, values) page-pool halves — READ-ONLY
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    keys: jax.Array,
    tables: jax.Array,  # int32 [B, n_table] per-row page tables
    matched: jax.Array,  # int32 [B] aliased prefix lengths (0 = no alias)
):
    """:func:`decode_chunk_batched` with zero-copy prefix aliasing: rows
    whose prompt hit the radix cache read their matched prefix straight out
    of the shared page pool every step — no gathered slab duplicate exists.
    Only the slab is donated; the pool is shared across every row and
    dispatch, so it must never alias. Same packed [n_steps + 2, B] return
    bundle as :func:`decode_chunk_batched`."""
    tokens, cache, keys, h, okf = batched_decode_scan(
        cfg, params, first_tokens, cache, pos, active, keys, n_steps,
        temperature, topp, paged=(pool, tables, matched),
    )
    return integrity.pack_chunk_outputs(tokens, h, okf), cache, keys


# ---------------------------------------------------------------------------
# Self-speculative decoding (prompt-lookup drafts, Leviathan et al. verify):
# the host proposes up to k draft tokens from the request's own prompt +
# output n-grams (engine/speculative.py — no draft model), one verify
# forward scores [prev, d_1..d_k] in a single weight read, and the
# accept/reject below runs ON DEVICE so only (n_emit, tokens) — a handful
# of int32s — cross the host boundary per step.
# ---------------------------------------------------------------------------


def _spec_accept_row(logits, draft, draft_len, key, temperature, topp):
    """Accept/reject one row's draft against its verify logits.

    ``logits``: [T, vocab] f32 (T = k + 1) — ``logits[i]`` is the model's
    next-token distribution after consuming feed position ``i``;
    ``draft``: [k] int32 (entries at or beyond ``draft_len`` are pad);
    ``temperature``/``topp``: traced scalars. Returns
    ``(n_emit, tokens [T], new_key)`` where ``tokens[:n_emit]`` are the
    emitted tokens — ``n_emit - 1`` accepted drafts plus one
    correction/bonus token drawn from the model's own distribution.

    Greedy (temperature == 0): longest-matching-prefix against the argmax
    targets — every emitted token IS the plain decode's argmax at its
    position, so the stream is bit-identical to non-speculative decode.

    Sampled: Leviathan-style rejection sampling. The prompt-lookup draft
    distribution is the point mass q = δ(draft_i), so position i accepts
    with probability p_i(draft_i) (p = the post-temperature/top-p filtered
    softmax — exactly what :func:`_sample_token_dynamic` samples from) and
    a rejection redraws from the residual norm(max(p - q, 0)) = p with
    draft_i removed; acceptance never biases the output distribution."""
    T, vocab = logits.shape
    k = T - 1
    greedy_targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [T]
    # the filtered target distribution, constructed identically to
    # _sample_token_dynamic (fast-path threshold == full-sort threshold)
    scaled = logits / jnp.maximum(temperature, 1e-6)
    probs = jax.vmap(jax.nn.softmax)(scaled)  # [T, vocab]
    thresholds = jax.vmap(_topp_threshold, in_axes=(0, None))(probs, topp)
    use_topp = (topp > 0.0) & (topp < 1.0)
    filtered = jnp.where(use_topp & (probs < thresholds[:, None]), -jnp.inf, scaled)
    p = jax.vmap(jax.nn.softmax)(filtered)  # [T, vocab] — renormalized

    split = jax.random.split(key, 2 * T + 1)
    new_key, u_keys, draw_keys = split[0], split[1 : T + 1], split[T + 1 :]

    i_idx = jnp.arange(k)
    in_draft = i_idx < draft_len
    p_draft = p[i_idx, draft]  # [k] acceptance probability per position
    u = jax.vmap(jax.random.uniform)(u_keys[:k]) if k else jnp.zeros((0,))
    sampled_ok = u < p_draft
    greedy_ok = draft == greedy_targets[:k]
    ok = jnp.where(temperature == 0.0, greedy_ok, sampled_ok) & in_draft
    acc = jnp.cumprod(ok.astype(jnp.int32)) if k else jnp.zeros((0,), jnp.int32)
    n_acc = jnp.sum(acc)  # accepted draft prefix length

    # one categorical per position (T is small): the residual draw for a
    # rejection at i < draft_len, the full draw for the bonus position
    resid_logits = jnp.where(
        jnp.arange(vocab)[None, :] == draft[:, None], -jnp.inf, filtered[:k]
    )
    resid = (
        jax.vmap(jax.random.categorical)(draw_keys[:k], resid_logits).astype(jnp.int32)
        if k
        else jnp.zeros((0,), jnp.int32)
    )
    full = jax.vmap(jax.random.categorical)(draw_keys, filtered).astype(jnp.int32)
    resid_padded = jnp.concatenate([resid, jnp.zeros((1,), jnp.int32)])
    rejected = n_acc < draft_len
    corr_sampled = jnp.where(rejected, resid_padded[n_acc], full[n_acc])
    corr = jnp.where(temperature == 0.0, greedy_targets[n_acc], corr_sampled)

    t_idx = jnp.arange(T)
    draft_padded = jnp.concatenate([draft, jnp.zeros((1,), jnp.int32)])
    tokens = jnp.where(t_idx < n_acc, draft_padded, 0)
    tokens = jnp.where(t_idx == n_acc, corr, tokens).astype(jnp.int32)
    return (n_acc + 1).astype(jnp.int32), tokens, new_key


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_step(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [T] — [prev, draft_1..draft_k] (pad beyond draft_len)
    cache,
    pos: jax.Array,  # int32 scalar: position of feed[0]
    draft_len: jax.Array,  # int32 scalar
    temperature: jax.Array,
    topp: jax.Array,
    key: jax.Array,
):
    """One single-stream speculative step: verify forward (the ordinary
    multi-token decode at a position offset — ONE weight read for draft +
    bonus positions) fused with the on-device accept/reject. Returns
    ``(out, cache, key)`` with ``out = [n_emit, tokens...]`` int32 [T+1] —
    the only bytes that visit the host. Cache slots past the accepted
    prefix hold rejected-draft K/V: stale but unreachable (the next step
    writes at the advanced position before any query can see them — the
    same overshoot contract as the chunked decode's rollback)."""
    logits, cache = llama.forward_tokens(cfg, params, feed, cache, pos)
    n_emit, tokens, key = _spec_accept_row(
        logits, feed[1:], draft_len, key, temperature, topp
    )
    return jnp.concatenate([n_emit[None], tokens]), cache, key


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_chunk_batched(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [B, T] per-row [prev, drafts...] windows
    cache,
    pos: jax.Array,  # int32 [B]
    active: jax.Array,  # bool [B]
    draft_len: jax.Array,  # int32 [B]
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    keys: jax.Array,  # [B, 2]
):
    """One batched speculative step: every joined row's verify window rides
    ONE weight read (llama.forward_verify_batched) and the per-row
    accept/reject runs on device. Returns ``(out [B, T+1], cache,
    new_keys)`` with ``out[b] = [n_emit_b, tokens_b...]`` — rows advance a
    VARIABLE number of positions per step (the scheduler applies each
    row's n_emit at fetch time). Inactive rows compute garbage into
    dropped cache slots, exactly like the plain batched chunk."""
    logits, cache = llama.forward_verify_batched(
        cfg, params, feed, cache, pos, active
    )
    n_emit, tokens, new_keys = jax.vmap(_spec_accept_row)(
        logits, feed[:, 1:], draft_len, keys, temperature, topp
    )
    return jnp.concatenate([n_emit[:, None], tokens], axis=1), cache, new_keys


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def spec_verify_chunk_batched_paged(
    cfg: LlamaConfig,
    params,
    feed: jax.Array,  # int32 [B, T] per-row [prev, drafts...] windows
    cache,
    pos: jax.Array,  # int32 [B]
    active: jax.Array,  # bool [B]
    pool,  # per-layer (keys, values) page-pool halves — READ-ONLY
    draft_len: jax.Array,  # int32 [B]
    temperature: jax.Array,  # [B]
    topp: jax.Array,  # [B]
    keys: jax.Array,  # [B, 2]
    tables: jax.Array,  # int32 [B, n_table]
    matched: jax.Array,  # int32 [B]
):
    """:func:`spec_verify_chunk_batched` with zero-copy prefix aliasing:
    verify windows attend over pool pages for the matched prefix and the
    slab row for the private suffix, bit-identical to the copied-prefix
    verify (the spec × prefix-cache parity contract)."""
    logits, cache = llama.forward_verify_batched(
        cfg, params, feed, cache, pos, active, paged=(pool, tables, matched)
    )
    n_emit, tokens, new_keys = jax.vmap(_spec_accept_row)(
        logits, feed[:, 1:], draft_len, keys, temperature, topp
    )
    return jnp.concatenate([n_emit[:, None], tokens], axis=1), cache, new_keys


@functools.partial(jax.jit, static_argnums=(0, 5), donate_argnums=(3,))
def decode_chunk(
    cfg: LlamaConfig,
    params,
    first_token: jax.Array,
    cache: jax.Array,
    pos: jax.Array,
    n_steps: int,
    temperature: jax.Array,
    topp: jax.Array,
    key: jax.Array,
):
    """One chunk of the user-facing streaming decode (single chip): like
    :func:`decode_loop` but temperature/topp are *traced* scalars — one
    compiled program per chunk size serves every request's sampler settings —
    and the advanced PRNG key is returned so the stream continues across
    chunks exactly as a single dispatch would."""
    return decode_scan(
        cfg, params, first_token, cache, pos, key, n_steps, temperature, topp
    )
