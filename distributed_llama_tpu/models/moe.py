"""Mixture-of-experts FFN (Mixtral, Grok-1).

Parity with the reference's MoE task chain (reference:
src/grok1-tasks.cpp:56-263, composed into Mixtral at
src/mixtral-tasks.cpp:25-44): router matmul → softmax → top-k →
renormalized weights → per-expert SwiGLU → weighted sum of expert downs.

TPU-first design notes:
* The reference routes on the root with scalar code and broadcasts indexes
  (grok1-tasks.cpp:69-126); here routing is `jax.lax.top_k` inside the same
  jitted program — replicated across TP shards, so no broadcast exists.
* Experts are TP-sliced exactly like the reference (every shard holds a
  1/n-of-hidden slice of *all* experts — transformer.cpp:335-353), so the
  expert weighted-sum needs the same single psum as the dense FFN.
* Expert mixing is dense one-hot (every expert computed, weighted by a
  mostly-zero [T, E] matrix). For the single-token decode path this trades
  (E/k)× MXU flops for zero dynamic gathers; a top-k gathered variant is the
  planned Pallas optimization (SURVEY.md §7 stage 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_llama_tpu.formats.model_file import ArchType
from distributed_llama_tpu.models.config import LlamaConfig


def router_weights(cfg: LlamaConfig, xn: jax.Array, router: jax.Array) -> jax.Array:
    """[T, E] mixing weights: softmax over all experts, top-k selected, the
    selected weights renormalized to sum to 1 (reference:
    src/grok1-tasks.cpp:62-114)."""
    logits = jnp.einsum(
        "td,de->te",
        xn.astype(jnp.float32),
        router.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, cfg.n_active_experts)
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32)  # [T, k, E]
    return jnp.einsum("tk,tke->te", top_vals, one_hot)


def moe_ffn(cfg: LlamaConfig, xn: jax.Array, lp, axis_name: str | None) -> jax.Array:
    """Expert-mixed SwiGLU. ``xn``: [T, dim] (already normed);
    lp["moe_up"/"moe_gate"]: [E, dim, hidden_local], lp["moe_down"]:
    [E, hidden_local, dim]; returns [T, dim] (psum'd over TP shards)."""
    from distributed_llama_tpu.models.llama import _activation  # no cycle at call time

    weights = router_weights(cfg, xn, lp["router"])  # [T, E] f32
    xc = xn.astype(lp["moe_up"].dtype)
    gate = jnp.einsum(
        "td,edh->teh", xc, lp["moe_gate"], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    up = jnp.einsum(
        "td,edh->teh", xc, lp["moe_up"], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    h = _activation(gate, cfg.hidden_act) * up  # [T, E, Hl] f32
    down = jnp.einsum(
        "teh,ehd->ted", h.astype(lp["moe_down"].dtype), lp["moe_down"],
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST,
    )
    out = jnp.einsum("te,ted->td", weights, down, precision=jax.lax.Precision.HIGHEST)
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


def moe_block(cfg: LlamaConfig, x: jax.Array, lp, axis_name: str | None) -> jax.Array:
    """The FFN half of a MoE block, *after* the attention residual has been
    applied by the caller. Handles the Mixtral-vs-Grok norm placement."""
    from distributed_llama_tpu.models.llama import rmsnorm

    if cfg.arch == ArchType.GROK1:
        xn = rmsnorm(x, lp["rms_moe"])
        out = moe_ffn(cfg, xn, lp, axis_name)
        return x + rmsnorm(out.astype(x.dtype), lp["rms_ffn2"])
    xn = rmsnorm(x, lp["rms_ffn"])
    return x + moe_ffn(cfg, xn, lp, axis_name).astype(x.dtype)
