"""Mixture-of-experts FFN (Mixtral, Grok-1).

Parity with the reference's MoE task chain (reference:
src/grok1-tasks.cpp:56-263, composed into Mixtral at
src/mixtral-tasks.cpp:25-44): router matmul → softmax → top-k →
renormalized weights → per-expert SwiGLU → weighted sum of expert downs.

TPU-first design notes:
* The reference routes on the root with scalar code and broadcasts indexes
  (grok1-tasks.cpp:69-126); here routing is `jax.lax.top_k` inside the same
  jitted program — replicated across TP shards, so no broadcast exists.
* Experts are TP-sliced exactly like the reference (every shard holds a
  1/n-of-hidden slice of *all* experts — transformer.cpp:335-353), so the
  expert weighted-sum needs the same single psum as the dense FFN.
* Decode (T == 1) computes ONLY the top-k experts: each selected expert runs
  under a `lax.lax.switch` whose branches close over one expert's weights, so
  HBM reads and MXU flops scale with k, not E (top-2-of-8 Mixtral decode
  touches 4x less expert memory than dense mixing). Prefill (T > 1) keeps
  dense one-hot mixing: tokens fan out across experts anyway and the batched
  einsum keeps the MXU fed without per-token gathers.
* Expert banks may be Q40: `engine.weights` loads each expert as fused
  gate|up + down `QuantizedMatrix` leaves (an ``experts`` list in the layer
  params), so a Q40 Mixtral file occupies ~file-size HBM instead of
  inflating 4x to bf16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_llama_tpu.formats.model_file import ArchType
from distributed_llama_tpu.models.config import LlamaConfig


def router_probs(cfg: LlamaConfig, xn: jax.Array, router: jax.Array) -> jax.Array:
    """[T, E] softmax router probabilities (reference: src/grok1-tasks.cpp:62-97)."""
    logits = jnp.einsum(
        "td,de->te",
        xn.astype(jnp.float32),
        router.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    )
    return jax.nn.softmax(logits, axis=-1)


def router_topk(
    cfg: LlamaConfig, xn: jax.Array, router: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Top-k routing: ([T, k] renormalized weights, [T, k] expert ids) —
    the single home of the select-then-renormalize convention
    (reference: src/grok1-tasks.cpp:62-114)."""
    probs = router_probs(cfg, xn, router)
    top_vals, top_idx = jax.lax.top_k(probs, cfg.n_active_experts)
    return top_vals / jnp.sum(top_vals, axis=-1, keepdims=True), top_idx


def router_weights(cfg: LlamaConfig, xn: jax.Array, router: jax.Array) -> jax.Array:
    """[T, E] mixing weights: top-k selected, renormalized to sum to 1,
    zero elsewhere."""
    top_vals, top_idx = router_topk(cfg, xn, router)
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32)  # [T, k, E]
    return jnp.einsum("tk,tke->te", top_vals, one_hot)


def _expert_weights(lp, e: int):
    """Weights of expert ``e``: a dict with either fused ``gate_up`` (+
    ``down``) QuantizedMatrix leaves (the q40 layout) or separate
    ``gate``/``up``/``down`` slices of the stacked bf16 banks."""
    if "experts" in lp:
        return lp["experts"][e]
    return {"gate": lp["moe_gate"][e], "up": lp["moe_up"][e], "down": lp["moe_down"][e]}


def _expert_ffn(cfg: LlamaConfig, xn: jax.Array, ew) -> jax.Array:
    """One expert's SwiGLU on normed input [T, D] -> [T, D] f32 (pre-psum,
    pre-weighting). Mirrors the dense FFN's fused-vs-separate dispatch."""
    from distributed_llama_tpu.models.llama import _activation, _matmul

    if "gate_up" in ew:
        fused = _matmul(xn.astype(ew["gate_up"].dtype), ew["gate_up"])
        hidden = fused.shape[-1] // 2
        h = _activation(fused[:, :hidden], cfg.hidden_act) * fused[:, hidden:]
    else:
        xc = xn.astype(ew["gate"].dtype)
        h = _activation(_matmul(xc, ew["gate"]), cfg.hidden_act) * _matmul(xc, ew["up"])
    return _matmul(h.astype(ew["down"].dtype), ew["down"])


def _moe_topk(cfg: LlamaConfig, xn: jax.Array, lp) -> jax.Array:
    """Decode path: run exactly the k selected experts via lax.switch.
    Routing is replicated across shards (same input -> same indexes), the
    reference's index broadcast with the broadcast removed."""
    top_vals, top_idx = router_topk(cfg, xn, lp["router"])  # [1, k]
    top_vals, top_idx = top_vals[0], top_idx[0]
    branches = [
        (lambda x_, e=e: _expert_ffn(cfg, x_, _expert_weights(lp, e)))
        for e in range(cfg.n_experts)
    ]
    out = jnp.zeros(xn.shape, jnp.float32)
    for i in range(cfg.n_active_experts):
        out = out + top_vals[i] * jax.lax.switch(top_idx[i], branches, xn)
    return out


# below this many tokens the serial all-E path is used instead of the
# bucketed one: the capacity estimate is noisy at small T (drops bite) and
# expert-weight HBM reads dominate anyway, so bucketing's compute savings
# buy nothing
MOE_BUCKETED_MIN_T = 32


def bucket_capacity(factor: float, n_tokens: int, k: int, n_buckets: int) -> int:
    """Per-expert bucket rows. factor <= 0 = EXACT: n_tokens rows (a token
    routes to a given expert at most once, so that is the drop-free worst
    case). factor > 0 = standard capacity semantics: ceil(factor·T·k/E)
    rounded up to a multiple of 4, overflow rows drop."""
    import math

    if factor <= 0:
        return n_tokens
    return min(n_tokens, max(4, -(-math.ceil(factor * n_tokens * k / n_buckets) // 4) * 4))


def bucket_rank(top_idx: jax.Array, n_buckets: int):
    """Rank every (token, choice) within its target expert — the "sort" of
    the compacted buckets without an actual sort. top_idx: [T, k] expert
    ids. Returns (flat_e [T*k], rank [T*k], t_ids [T*k])."""
    T, k = top_idx.shape
    N = T * k
    flat_e = top_idx.reshape(N)
    onehot = jax.nn.one_hot(flat_e, n_buckets, dtype=jnp.int32)  # [N, E]
    rank = (jnp.cumsum(onehot, axis=0) - onehot)[jnp.arange(N), flat_e]
    t_ids = jnp.repeat(jnp.arange(T), k)
    return flat_e, rank, t_ids


def bucket_scatter(
    x: jax.Array, flat_e: jax.Array, rank: jax.Array, t_ids: jax.Array,
    n_buckets: int, C: int,
) -> jax.Array:
    """Gather each expert's routed rows into fixed [n_buckets, C, D]
    buckets; rows ranked past C land in a spill row that is trimmed
    (capacity drop). An expert index >= n_buckets drops the row entirely
    (the pad-token sink of the bucketed prefill)."""
    D = x.shape[-1]
    slot = jnp.where(rank < C, rank, C)
    return (
        jnp.zeros((n_buckets, C + 1, D), x.dtype)
        .at[flat_e, slot]
        .set(x[t_ids], mode="drop")
    )[:, :C]


def bucket_combine(
    outs: jax.Array,  # [n_buckets, C, D] per-expert outputs (f32)
    top_idx: jax.Array,  # [T, k]
    rank: jax.Array,  # [T*k]
    top_vals: jax.Array,  # [T, k] renormalized weights
    C: int,
) -> jax.Array:
    """Combine expert outputs back to token order; dropped choices
    contribute zero. Returns [T, D] f32."""
    T, k = top_idx.shape
    rank = rank.reshape(T, k)
    valid = (rank < C).astype(jnp.float32)
    gathered = outs[top_idx, jnp.minimum(rank, C - 1)]  # [T, k, D]
    return jnp.einsum("tk,tkd->td", top_vals * valid, gathered)


def _moe_dense(
    cfg: LlamaConfig, xn: jax.Array, lp, n_real: jax.Array | None = None
) -> jax.Array:
    """Prefill path: every expert computed, mixed by the mostly-zero [T, E]
    weight matrix. For stacked bf16 banks this is one batched einsum; for
    per-expert q40 leaves: serial all-E by default (exact), or — with an
    opted-in capacity factor (cfg.moe_capacity_factor, the --moe-capacity
    flag) — gather-to-expert-buckets + per-expert batched fused matmuls
    (each expert computes only ~factor·T·k/E rows instead of all T, at the
    cost of capacity drops under routing imbalance). ``n_real`` marks the
    real-token prefix of a bucket-padded batch; the bucketed path masks the
    pad rows out of its expert buckets (they must not spend capacity)."""
    if "experts" in lp:
        if cfg.moe_capacity_factor > 0 and xn.shape[0] >= MOE_BUCKETED_MIN_T:
            return _moe_dense_bucketed(cfg, xn, lp, n_real=n_real)
        weights = router_weights(cfg, xn, lp["router"])  # [T, E] f32
        out = jnp.zeros(xn.shape, jnp.float32)
        for e in range(cfg.n_experts):
            out = out + weights[:, e : e + 1] * _expert_ffn(
                cfg, xn, _expert_weights(lp, e)
            )
        return out
    weights = router_weights(cfg, xn, lp["router"])  # [T, E] f32
    from distributed_llama_tpu.models.llama import _activation

    if lp["moe_up"].dtype == jnp.bfloat16 and jax.default_backend() == "cpu":
        # some XLA:CPU builds cannot EXECUTE bf16xbf16 batched dots
        # ("DotThunk ... BF16 x BF16" runtime errors); f32 operands cost
        # nothing on the dev/test surface and TPU never takes this branch
        lp = dict(lp)
        for k_ in ("moe_up", "moe_gate", "moe_down"):
            lp[k_] = lp[k_].astype(jnp.float32)
    xc = xn.astype(lp["moe_up"].dtype)
    gate = jnp.einsum(
        "td,edh->teh", xc, lp["moe_gate"], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    up = jnp.einsum(
        "td,edh->teh", xc, lp["moe_up"], preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    h = _activation(gate, cfg.hidden_act) * up  # [T, E, Hl] f32
    down = jnp.einsum(
        "teh,ehd->ted", h.astype(lp["moe_down"].dtype), lp["moe_down"],
        preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.einsum("te,ted->td", weights, down, precision=jax.lax.Precision.HIGHEST)


def _moe_dense_bucketed(
    cfg: LlamaConfig, xn: jax.Array, lp, n_real: jax.Array | None = None
) -> jax.Array:
    """Capacity-bucketed q40 prefill: rank every (token, choice) within its
    expert, gather each expert's rows into a fixed [C, D] bucket, run ONE
    fused q40 FFN per expert over its bucket, and combine outputs with the
    renormalized top-k weights. Compute per expert drops from T rows to
    C ≈ factor·T·k/E (4x less for Mixtral's 2-of-8 at factor 2; measured
    +15% prefill at T=128, docs/PERF.md); the expert-weight HBM reads are
    identical, so the win scales with T. The bucket algebra
    (bucket_rank/scatter/combine) is shared with the expert-parallel
    dispatch (parallel.expert_parallel._ep_dispatch).

    Engine bucket-padding appends zero tokens past ``n_real``; those rows
    route like real tokens (identical embeddings → identical experts), so
    unmasked they would pile into a few experts' buckets. They are routed
    to a sink index E instead: the one-hot rank treats them as absent and
    the scatter drops them, so capacity is spent ONLY on real tokens (the
    capacity C itself must stay a static function of the padded T)."""
    T, D = xn.shape
    E = cfg.n_experts
    k = cfg.n_active_experts
    top_vals, top_idx = router_topk(cfg, xn, lp["router"])  # [T, k]
    if n_real is not None:
        valid = jnp.arange(T) < n_real
        top_idx = jnp.where(valid[:, None], top_idx, E)  # sink: pads drop

    C = bucket_capacity(cfg.moe_capacity_factor, T, k, E)
    flat_e, rank, t_ids = bucket_rank(top_idx, E)
    buckets = bucket_scatter(xn, flat_e, rank, t_ids, E, C)

    outs = jnp.stack([
        _expert_ffn(cfg, buckets[e], _expert_weights(lp, e)) for e in range(E)
    ])  # [E, C, D] f32
    return bucket_combine(outs, top_idx, rank, top_vals, C)


def moe_ffn(
    cfg: LlamaConfig, xn: jax.Array, lp, axis_name: str | None,
    ep_axis: str | None = None, n_real: jax.Array | None = None,
) -> jax.Array:
    """Expert-mixed SwiGLU. ``xn``: [T, dim] (already normed); returns
    [T, dim] (psum'd over TP shards). With ``ep_axis`` set the expert banks
    in ``lp`` are SHARDED over that mesh axis (device owns E/ep whole
    experts) and the exchange runs in parallel.expert_parallel — the psum
    over ``axis_name`` (hidden-slice partial sums under TP) still applies on
    top. ``n_real`` (bucket-padded prefill) reaches only the capacity-
    bucketed dense path; the exact paths compute pads harmlessly."""
    if ep_axis is not None:
        from distributed_llama_tpu.parallel.expert_parallel import ep_moe_ffn

        out = ep_moe_ffn(cfg, xn, lp, ep_axis)
    elif xn.shape[0] == 1:
        out = _moe_topk(cfg, xn, lp)
    else:
        out = _moe_dense(cfg, xn, lp, n_real=n_real)
    if axis_name is not None:
        # the MoE combine rides the same all-reduce seam as the dense FFN
        # (ops.collectives: psum off-TPU, the ICI ring kernel on TPU)
        from distributed_llama_tpu.ops import collectives

        out = collectives.all_reduce(out, axis_name)
    return out


def moe_block(
    cfg: LlamaConfig, x: jax.Array, lp, axis_name: str | None,
    ep_axis: str | None = None, n_real: jax.Array | None = None,
) -> jax.Array:
    """The FFN half of a MoE block, *after* the attention residual has been
    applied by the caller. Handles the Mixtral-vs-Grok norm placement."""
    from distributed_llama_tpu.models.llama import rmsnorm

    if cfg.arch == ArchType.GROK1:
        xn = rmsnorm(x, lp["rms_moe"])
        out = moe_ffn(cfg, xn, lp, axis_name, ep_axis=ep_axis, n_real=n_real)
        return x + rmsnorm(out.astype(x.dtype), lp["rms_ffn2"])
    xn = rmsnorm(x, lp["rms_ffn"])
    return x + moe_ffn(
        cfg, xn, lp, axis_name, ep_axis=ep_axis, n_real=n_real
    ).astype(x.dtype)
