"""JAX platform selection helper.

The container's sitecustomize may register a TPU plugin and pin
``jax_platforms`` before user code runs, which silently beats the
``JAX_PLATFORMS`` env var. Every entry point that honors the env var
(CLI, API server, driver entry) calls :func:`reassert_jax_platforms`
right after importing jax.
"""

from __future__ import annotations

import os


def reassert_jax_platforms() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment over any pinned
    jax_platforms config (must run before first device initialization)."""
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        jax.config.update("jax_platforms", env)


_cache_hit_listener_installed = False


def _install_cache_hit_listener() -> None:
    """Count persistent-cache hits into telemetry: jax announces each
    cache-served compile via a monitoring event; the listener forwards it
    to ``dllama_compile_cache_hits_total`` (no-op while telemetry is off).
    Best-effort — the monitoring module is a private jax API, so a missing
    symbol just loses the counter, never the cache."""
    global _cache_hit_listener_installed
    if _cache_hit_listener_installed:
        return
    try:
        from jax._src import monitoring

        def _on_event(event: str, **kwargs) -> None:
            if event == "/jax/compilation_cache/cache_hits":
                from distributed_llama_tpu import telemetry

                telemetry.note_compile_cache_hit()

        monitoring.register_event_listener(_on_event)
        _cache_hit_listener_installed = True
    except Exception:
        pass


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a directory so a fresh
    process reuses compiled programs instead of re-compiling the model
    (measured 22.5 s for a cold 32-layer Q40 7B prefill program, BENCH_r03;
    the 8.6 s cold-prefill number of BENCH_r05 is this compile).

    Called by every entry point (CLI, API server, bench) before the first
    jit. Resolution order: explicit argument (the ``--compile-cache-dir``
    flag), ``DLLAMA_COMPILE_CACHE`` env var, legacy ``DLT_COMPILE_CACHE``
    (empty string disables), else ``~/.cache/distributed_llama_tpu/xla``.
    Returns the directory in use, or None when disabled or unavailable.
    Cache-served compiles are counted in ``dllama_compile_cache_hits_total``
    when telemetry is enabled."""
    if cache_dir is None:
        cache_dir = os.environ.get(
            "DLLAMA_COMPILE_CACHE", os.environ.get("DLT_COMPILE_CACHE")
        )
        if cache_dir == "":
            return None
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "distributed_llama_tpu", "xla"
        )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip small programs and would also skip fast
        # RECOMPILES of big ones; cache everything that took >1s to build
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _install_cache_hit_listener()
        return cache_dir
    except Exception:
        return None  # cache is an optimization; never block startup on it


def virtual_cpu_mesh_env(n_devices: int) -> dict[str, str]:
    """Environment for a child process running on an ``n_devices``-way
    virtual CPU mesh — the no-hardware test substrate for multi-chip code
    (same recipe as tests/conftest.py, forced rather than append-if-absent)."""
    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env
