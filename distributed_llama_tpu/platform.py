"""JAX platform selection helper.

The container's sitecustomize may register a TPU plugin and pin
``jax_platforms`` before user code runs, which silently beats the
``JAX_PLATFORMS`` env var. Every entry point that honors the env var
(CLI, API server, driver entry) calls :func:`reassert_jax_platforms`
right after importing jax.
"""

from __future__ import annotations

import os


def reassert_jax_platforms() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment over any pinned
    jax_platforms config (must run before first device initialization)."""
    env = os.environ.get("JAX_PLATFORMS")
    if env:
        import jax

        jax.config.update("jax_platforms", env)


def virtual_cpu_mesh_env(n_devices: int) -> dict[str, str]:
    """Environment for a child process running on an ``n_devices``-way
    virtual CPU mesh — the no-hardware test substrate for multi-chip code
    (same recipe as tests/conftest.py, forced rather than append-if-absent)."""
    env = dict(os.environ)
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env
