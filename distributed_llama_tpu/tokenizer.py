"""Tokenizer, sampler, chat templates and streaming stop detection.

Capability parity with the reference's `src/tokenizer.cpp` (SentencePiece-style
BPE encode at tokenizer.cpp:170-292, decode at 150-161, Sampler at 294-415,
ChatTemplate at 436-500, EosDetector at 502-575) — reimplemented for a host
Python runtime driving a TPU model. The vocabulary is kept as raw ``bytes``
(the reference's char* vocab), so arbitrary byte-fallback tokens round-trip.

The sampler here is the *host* sampler used by the CLI for parity with the
reference's semantics (including its xorshift RNG so seeded runs match).
The TPU decode loop has an additional on-device sampler (see
``distributed_llama_tpu.models.sampling``) that avoids per-token host sync.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable, Sequence

import numpy as np

from distributed_llama_tpu.formats.tokenizer_file import TokenizerData, read_tokenizer_file

_RAW_BYTE_RE = re.compile(rb"^<0x([0-9A-Fa-f]{2})>$")


class Tokenizer:
    """Byte-level SentencePiece/BPE tokenizer over a `.t` vocabulary.

    Encode algorithm (reference: src/tokenizer.cpp:170-292): optional BOS,
    optional dummy-prefix space token, UTF-8 codepoint split with byte
    fallback (+3 offset), then greedy highest-score pair merging.
    """

    def __init__(self, data: TokenizerData):
        self.data = data
        self.vocab: list[bytes] = data.vocab
        self.scores: list[float] = data.scores
        self.bos_id = data.bos_id
        self.eos_id = data.eos_id
        self.chat_eos_id = data.chat_eos_id
        self.chat_template = data.chat_template
        self.chat_stop = data.chat_stop
        # first-wins (lowest id) for duplicate pieces; the reference's
        # qsort+bsearch resolves duplicates arbitrarily, a dict is
        # deterministic and O(1)
        self._index: dict[bytes, int] = {}
        for i, tok in enumerate(self.vocab):
            self._index.setdefault(tok, i)
        # the O(n^2) split+merge core runs natively when the host lib is
        # built (same algorithm, see native/bpe_native.cpp)
        self._native = None
        try:
            from distributed_llama_tpu import native

            if native.available():
                self._native = native.NativeBpe(self.vocab, self.scores)
        except Exception:
            self._native = None

    @classmethod
    def from_file(cls, path: str, model_vocab_size: int | None = None) -> "Tokenizer":
        data = read_tokenizer_file(path)
        if model_vocab_size is not None and data.vocab_size != model_vocab_size:
            raise ValueError(
                f"tokenizer vocab size {data.vocab_size} != model vocab size {model_vocab_size}"
            )
        return cls(data)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def encode(self, text: str | bytes, add_bos: bool = False, add_eos: bool = False) -> list[int]:
        if isinstance(text, str):
            text = text.encode("utf-8")
        tokens: list[int] = []
        if add_bos:
            tokens.append(self.bos_id)

        if self._native is not None:
            # the dummy-prefix space token participates in merging exactly as
            # if the text began with a literal space (it is the " " piece)
            prefixed = (b" " if text and b" " in self._index else b"") + text
            tokens.extend(self._native.encode(prefixed))
            if add_eos:
                tokens.append(self.eos_id)
            return tokens

        # dummy prefix space (sentencepiece add_dummy_prefix;
        # reference: src/tokenizer.cpp:198-207)
        if text:
            space_id = self._index.get(b" ")
            if space_id is not None:
                tokens.append(space_id)

        # split into UTF-8 codepoints (≤4 bytes), byte-fallback unknown ones
        i = 0
        n = len(text)
        while i < n:
            j = i + 1
            # extend while continuation bytes, capped at 4 bytes total
            while j < n and (text[j] & 0xC0) == 0x80 and (j - i) < 4:
                j += 1
            piece = text[i:j]
            tid = self._index.get(piece)
            if tid is not None:
                tokens.append(tid)
            else:
                # byte fallback: first 3 vocab entries are <unk>, <s>, </s>
                # (reference: src/tokenizer.cpp:247-252)
                tokens.extend(b + 3 for b in piece)
            i = j

        # greedy merge: repeatedly replace the adjacent pair whose
        # concatenation has the best vocab score
        # (reference: src/tokenizer.cpp:257-286)
        while True:
            best_score = -1e10
            best_id = -1
            best_idx = -1
            for k in range(len(tokens) - 1):
                merged = self.vocab[tokens[k]] + self.vocab[tokens[k + 1]]
                mid = self._index.get(merged)
                if mid is not None and self.scores[mid] > best_score:
                    best_score = self.scores[mid]
                    best_id = mid
                    best_idx = k
            if best_idx == -1:
                break
            tokens[best_idx : best_idx + 2] = [best_id]

        if add_eos:
            tokens.append(self.eos_id)
        return tokens

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        """Decode a single token following ``prev_token`` to raw bytes.

        Mirrors reference src/tokenizer.cpp:150-161: strips one leading space
        after BOS and converts `<0xNN>` raw-byte pieces to their byte. (The
        reference gates the raw-byte branch on ``sscanf(...) == bosId``, which
        only fires when bosId==1 — true for every sentencepiece vocab that
        actually contains `<0xNN>` pieces, so matching the pattern directly is
        behaviorally identical.)
        """
        piece = self.vocab[token]
        if prev_token == self.bos_id and piece.startswith(b" "):
            piece = piece[1:]
        m = _RAW_BYTE_RE.match(piece)
        if m:
            return bytes([int(m.group(1), 16)])
        return piece

    def decode(self, tokens: Sequence[int]) -> str:
        out = bytearray()
        prev = self.bos_id
        for t in tokens:
            if t == self.bos_id:
                prev = t
                continue
            out += self.decode_piece(prev, t)
            prev = t
        return out.decode("utf-8", errors="replace")


def is_safe_piece(piece: bytes) -> bool:
    """Filter lone control bytes (reference: src/tokenizer.cpp:19-31).

    Deliberate deviation from the reference's C-locale isprint: lone bytes
    >= 0x80 are KEPT — they are byte-fallback fragments of multi-byte UTF-8
    (e.g. 'é' emitted as <0xC3><0xA9>) that downstream byte buffers
    (EosDetector, the API chunker) reassemble into real characters; the
    reference silently drops them. Lone ASCII control bytes (except
    whitespace) and DEL are still unsafe."""
    if not piece:
        return False
    if len(piece) == 1:
        b = piece[0]
        if b < 0x20:
            return b in (0x09, 0x0A, 0x0B, 0x0C, 0x0D)
        return b != 0x7F
    return True


# ---------------------------------------------------------------------------
# RNG + sampling (host path)
# ---------------------------------------------------------------------------


class XorshiftRng:
    """xorshift64* RNG, bit-identical to the reference for seeded parity
    (reference: src/utils.cpp:79-90)."""

    MASK = (1 << 64) - 1

    def __init__(self, seed: int):
        self.state = seed & self.MASK

    def next_u32(self) -> int:
        s = self.state
        s ^= (s >> 12)
        s ^= (s << 25) & self.MASK
        s ^= (s >> 27)
        self.state = s
        return ((s * 0x2545F4914F6CDD1D) & self.MASK) >> 32

    def next_f32(self) -> float:
        return (self.next_u32() >> 8) / 16777216.0


def _softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max()
    e = np.exp(x, dtype=np.float64)
    return (e / e.sum()).astype(np.float32)


@dataclasses.dataclass
class Sampler:
    """Greedy / temperature / top-k / top-p sampling on host logits
    (reference: src/tokenizer.cpp:371-415).

    Two RNG modes:

    * legacy (``counter=False``): the reference's sequential xorshift64*
      state — one coin per call in call order, bit-identical to the
      reference's draw sequence (the interop contract).
    * counter (``counter=True``): the stateless counter PRNG of
      :mod:`distributed_llama_tpu.prng`, coin keyed ``(seed, pos)`` — the
      host half of the device-sampling parity contract (ISSUE 13). Fed
      the same f32 logits, this mode replays a device-sampled stream
      token for token: identical candidate order (descending scaled
      logit, ties by id), identical f32 filter/CDF arithmetic, identical
      coins. ``sample`` then REQUIRES ``pos`` (the absolute position of
      the consumed token). Exact on the filtered (top-k/top-p) paths;
      the unfiltered multinomial path walks a full-vocab cumsum whose
      device counterpart may associate differently by ulps.

    Every ``sample`` call counts toward
    ``dllama_host_sampler_fallback_total``: with the fused device sampler
    in place, host sampling IS the fallback path."""

    vocab_size: int
    temperature: float = 0.8
    topp: float = 0.9
    seed: int = 0
    topk: int = 0
    counter: bool = False

    def __post_init__(self):
        self._rng = XorshiftRng(self.seed)
        from distributed_llama_tpu import prng as _prng

        self._seed32 = _prng.fold_seed(self.seed)
        # sampler-distribution counters (ISSUE 1): bound once per sampler —
        # shared no-op singletons when telemetry is disabled, so the
        # per-token host-sampling path never touches the registry
        from distributed_llama_tpu import telemetry

        self._tel = telemetry.SamplerInstruments()

    def set_seed(self, seed: int) -> None:
        from distributed_llama_tpu import prng as _prng

        self.seed = seed
        self._rng = XorshiftRng(seed)
        self._seed32 = _prng.fold_seed(seed)

    def set_temperature(self, temperature: float) -> None:
        self.temperature = temperature

    def set_topk(self, topk: int) -> None:
        self.topk = int(topk)

    def _coin(self, pos: int | None) -> float:
        if not self.counter:
            return self._rng.next_f32()
        if pos is None:
            raise ValueError(
                "counter-mode Sampler.sample needs pos (the absolute "
                "position of the consumed token) to key its coin"
            )
        from distributed_llama_tpu import prng as _prng

        return float(_prng.coin_f32(self._seed32, pos, _prng.DRAW_SAMPLE))

    def sample(self, logits: np.ndarray, pos: int | None = None) -> int:
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]
        if not np.isfinite(logits).all():
            # validate BEFORE sampling (ISSUE 10 satellite): NaN/Inf
            # logits pushed through the softmax/CDF below launder into a
            # perfectly in-vocab token id — the device path's
            # out-of-vocab check never sees it, and greedy argmax just
            # returns the first NaN's index. Fail typed instead; the
            # serving layer retires the request like any corrupt chunk.
            from distributed_llama_tpu.engine import faults

            raise faults.NonFiniteLogits(
                "host sampler got non-finite logits "
                f"({int((~np.isfinite(logits)).sum())} of {logits.size} "
                "entries); refusing to sample a plausible-but-wrong token"
            )
        self._tel.fallback.inc()
        if self.temperature == 0.0:
            self._tel.sampled.labels(method="greedy").inc()
            return int(np.argmax(logits))
        if self.counter or 0 < self.topk < logits.size:
            # top-k predates nothing: the legacy draw arithmetic never had
            # it, so an ACTIVE top-k always routes through the fused-pick
            # mirror (fed the legacy sequential coin when counter is off)
            # rather than being silently ignored
            return self._sample_counter(logits, self._coin(pos))
        probs = _softmax(logits / self.temperature)
        coin = self._coin(pos)
        if self.topp <= 0 or self.topp >= 1:
            self._tel.sampled.labels(method="multinomial").inc()
            return self._sample_mult(probs, coin)
        self._tel.sampled.labels(method="topp").inc()
        return self._sample_topp(probs, coin)

    def _sample_counter(self, logits: np.ndarray, coin: float) -> int:
        """The device fused sampler's arithmetic, op for op in f32
        (models/sampling.py ``fused_pick``): candidates ordered by
        descending temperature-scaled logit (ties by lower id), the kept
        prefix is min(top-k, nucleus), the draw is inverse-CDF over the
        kept prefix's f32 cumulative mass — same values, same coin, same
        pick as the device program this mode verifies."""
        n = logits.size
        scaled = (logits / np.float32(self.temperature)).astype(np.float32)
        m = scaled.max()
        e = np.exp(scaled - m, dtype=np.float32)
        probs = (e / e.sum(dtype=np.float32)).astype(np.float32)
        coin = np.float32(coin)
        topp_act = 0.0 < self.topp < 1.0
        topk_act = 0 < self.topk < n
        if not (topp_act or topk_act):
            # multinomial: vocab-order inverse CDF over the full mass
            self._tel.sampled.labels(method="multinomial").inc()
            cdf = np.cumsum(probs, dtype=np.float32)
            r = coin * cdf[-1]
            return min(int(np.sum(cdf <= r)), n - 1)
        self._tel.sampled.labels(method="topp" if topp_act else "topk").inc()
        order = np.argsort(-scaled, kind="stable")
        vals = probs[order]
        cum = np.cumsum(vals, dtype=np.float32)
        n_nuc = int(np.sum(cum - vals < np.float32(self.topp))) if topp_act else n
        n_k = self.topk if topk_act else n
        n_keep = max(1, min(n_nuc, n_k, n))
        total = cum[n_keep - 1]
        r = coin * total
        idx = min(int(np.sum(cum[:n_keep] <= r)), n_keep - 1)
        return int(order[idx])

    @staticmethod
    def _sample_mult(probs: np.ndarray, coin: float) -> int:
        cdf = np.cumsum(probs, dtype=np.float64)
        idx = int(np.searchsorted(cdf, coin, side="right"))
        return min(idx, probs.size - 1)

    def _sample_topp(self, probs: np.ndarray, coin: float) -> int:
        n = probs.size
        # pre-filter: values below (1-topp)/(n-1) can never be in the nucleus
        # (reference: src/tokenizer.cpp:334-345)
        cutoff = (1.0 - self.topp) / (n - 1)
        cand = np.nonzero(probs >= cutoff)[0]
        order = cand[np.argsort(-probs[cand], kind="stable")]
        sorted_probs = probs[order]
        cum = np.cumsum(sorted_probs, dtype=np.float64)
        # truncate where cumulative prob exceeds topp (inclusive)
        over = np.nonzero(cum > self.topp)[0]
        last_idx = int(over[0]) if over.size else order.size - 1
        total = cum[last_idx]
        r = coin * total
        idx = int(np.searchsorted(cum[: last_idx + 1], r, side="right"))
        idx = min(idx, last_idx)
        return int(order[idx])


# ---------------------------------------------------------------------------
# Chat templates
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ChatItem:
    role: str
    message: str


class ChatTemplateType:
    UNKNOWN = "unknown"
    LLAMA2 = "llama2"
    LLAMA3 = "llama3"
    ZEPHYR = "zephyr"
    CHATML = "chatml"


def detect_chat_template(template: str | None) -> str:
    """Substring-sniff the embedded jinja template
    (reference: src/tokenizer.cpp:440-450)."""
    if template is None:
        raise ValueError("the tokenizer does not include a chat template")
    if "[INST]" in template:
        return ChatTemplateType.LLAMA2
    if "<|start_header_id|>" in template:
        return ChatTemplateType.LLAMA3
    if "<|user|>" in template:
        return ChatTemplateType.ZEPHYR
    if "<|im_start|>" in template:
        return ChatTemplateType.CHATML
    raise ValueError("unsupported chat template")


class ChatTemplate:
    """Hardcoded renderers per detected template family
    (reference: src/tokenizer.cpp:468-500)."""

    def __init__(self, template_type: str, chat_template: str | None, eos: str):
        if template_type == ChatTemplateType.UNKNOWN:
            template_type = detect_chat_template(chat_template)
        self.type = template_type
        self.eos = eos

    def generate(self, items: Sequence[ChatItem], append_generation_prompt: bool = True) -> str:
        out: list[str] = []
        if self.type == ChatTemplateType.LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                out.append(
                    f"[INST] <<SYS>>\n{items[0].message}\n<</SYS>>\n\n{items[1].message} [/INST]{self.eos}"
                )
                i = 2
            for item in items[i:]:
                if item.role == "assistant":
                    out.append(f"{item.message}{self.eos}")
                elif item.role == "user":
                    out.append(f"[INST] {item.message} [/INST]{self.eos}")
        elif self.type == ChatTemplateType.LLAMA3:
            for item in items:
                out.append(
                    f"<|start_header_id|>{item.role}<|end_header_id|>\n\n{item.message}{self.eos}"
                )
            if append_generation_prompt:
                out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == ChatTemplateType.CHATML:
            for item in items:
                out.append(f"<|im_start|>{item.role}\n{item.message}<|im_end|>\n")
            if append_generation_prompt:
                out.append("<|im_start|>assistant\n")
        elif self.type == ChatTemplateType.ZEPHYR:
            for item in items:
                out.append(f"<|{item.role}|>\n{item.message}{self.eos}\n")
            if append_generation_prompt:
                out.append("<|assistant|>\n")
        else:
            raise ValueError(f"unsupported chat template type: {self.type}")
        return "".join(out)


def chat_stops(tokenizer: Tokenizer) -> list[str]:
    """Stop strings for chat mode: the chat EOS token text plus the optional
    extra stop string (reference: src/tokenizer.cpp:417-430)."""
    stops = [tokenizer.vocab[tokenizer.chat_eos_id].decode("utf-8", errors="replace")]
    if tokenizer.chat_stop:
        stops.append(tokenizer.chat_stop)
    return stops


# ---------------------------------------------------------------------------
# Streaming EOS / stop-sequence detection
# ---------------------------------------------------------------------------


class EosDetectorResult:
    NOT_EOS = 0
    EOS = 1
    MAYBE_EOS = 2


class EosDetector:
    """Streaming multi-token stop-string matcher.

    Buffers generated text; when a prefix of a stop string is seen at the tail
    the result is MAYBE_EOS (hold output), a full match is EOS, otherwise
    NOT_EOS and the buffered delta is safe to emit. ``padding_left`` allows a
    stop string to begin up to N characters into the buffer (tokens often glue
    whitespace before the stop marker); ``padding_right`` allows trailing
    characters after it (reference: src/tokenizer.cpp:502-575).
    """

    def __init__(
        self,
        eos_ids: int | Iterable[int],
        stops: Sequence[str],
        padding_left: int = 0,
        padding_right: int = 0,
    ):
        self.eos_ids = {eos_ids} if isinstance(eos_ids, int) else set(eos_ids)
        self.stops = [s.encode("utf-8") if isinstance(s, str) else s for s in stops]
        self.padding_left = padding_left
        self.padding_right = padding_right
        self.buffer = bytearray()
        self.eos_pos = -1

    def append(self, token_id: int, piece: bytes | str) -> int:
        if isinstance(piece, str):
            piece = piece.encode("utf-8")
        piece_len = len(piece)
        self.buffer += piece

        if token_id in self.eos_ids:
            self.eos_pos = len(self.buffer) - piece_len
            return EosDetectorResult.EOS
        self.eos_pos = -1

        for stop in self.stops:
            stop_size = len(stop)
            if len(self.buffer) > stop_size + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = len(self.buffer) - lo
                if n == 0 or n > stop_size + self.padding_right:
                    continue
                n = min(n, stop_size)
                if self.buffer[lo : lo + n] == stop[:n]:
                    if n == stop_size:
                        self.eos_pos = lo
                        return EosDetectorResult.EOS
                    return EosDetectorResult.MAYBE_EOS
        return EosDetectorResult.NOT_EOS

    def get_delta(self) -> bytes | None:
        """Text that is safe to emit after the last append()
        (reference: src/tokenizer.cpp:566-571)."""
        if self.eos_pos == -1:
            return bytes(self.buffer) if self.buffer else b""
        if self.eos_pos == 0:
            return None
        return bytes(self.buffer[: self.eos_pos])

    def flush_delta(self) -> bytes:
        """Drain buffered text on a non-EOS exit (length/context limit):
        text held back as a possible stop-string prefix (MAYBE_EOS) would
        otherwise be silently dropped. Clears the buffer."""
        delta = self.get_delta() or b""
        self.clear()
        return delta

    def clear(self) -> None:
        self.buffer = bytearray()
