"""Synthetic `.m` models: random seeded weights in the real file format.

The one shared implementation behind the test suite's tiny golden models
(tests/model_utils.py re-exports these) and the chaos bench
(``bench.py --chaos``) — the analogue of the reference's synthetic-spec
golden tests (src/llama2-tasks-test.cpp:531-565), with the xorshift weight
fill replaced by seeded numpy. Keeping it next to ModelFileWriter means the
init rules (rms weights near 1, everything else ~N(0, 1/sqrt(d_in))) and
the tensor-name layout cannot drift between consumers.
"""

from __future__ import annotations

import numpy as np

from distributed_llama_tpu.formats.model_file import (
    ArchType,
    HiddenAct,
    ModelFileWriter,
    ModelSpec,
    RopeType,
    tensor_layout,
)
from distributed_llama_tpu.quants import FloatType


def tiny_spec(**overrides) -> ModelSpec:
    """A CPU-friendly llama spec; override any field (seq_len, dims, ...)."""
    defaults = dict(
        arch_type=ArchType.LLAMA,
        dim=32,
        hidden_dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        vocab_size=64,
        seq_len=24,
        hidden_act=HiddenAct.SILU,
        rope_theta=10000.0,
        rope_type=RopeType.UNKNOWN,
        weights_float_type=FloatType.F32,
    )
    defaults.update(overrides)
    return ModelSpec(**defaults)


def random_tensors(spec: ModelSpec, seed: int = 0) -> dict[str, np.ndarray]:
    """Random weights keyed by the `.m` layout names, shaped [d_out, d_in]."""
    rng = np.random.RandomState(seed)
    out: dict[str, np.ndarray] = {}
    for e in tensor_layout(spec):
        if e.name.startswith("rms") or ".rms" in e.name:
            t = 1.0 + 0.1 * rng.randn(*e.shape)
        else:
            t = rng.randn(*e.shape) / np.sqrt(e.shape[-1])
        out[e.name] = t.astype(np.float32)
    return out


def write_model_file(path: str, spec: ModelSpec, tensors: dict[str, np.ndarray]) -> None:
    with open(path, "wb") as f:
        w = ModelFileWriter(f, spec)
        for e in w.remaining():
            w.write_tensor(tensors[e.name], e.name)


def write_synthetic_model(path: str, spec: ModelSpec, seed: int = 0) -> str:
    """One-call helper: random weights for ``spec`` written to ``path``."""
    write_model_file(path, spec, random_tensors(spec, seed=seed))
    return path
