"""Synthetic `.m` models: random seeded weights in the real file format.

The one shared implementation behind the test suite's tiny golden models
(tests/model_utils.py re-exports these) and the chaos bench
(``bench.py --chaos``) — the analogue of the reference's synthetic-spec
golden tests (src/llama2-tasks-test.cpp:531-565), with the xorshift weight
fill replaced by seeded numpy. Keeping it next to ModelFileWriter means the
init rules (rms weights near 1, everything else ~N(0, 1/sqrt(d_in))) and
the tensor-name layout cannot drift between consumers.
"""

from __future__ import annotations

import numpy as np

from distributed_llama_tpu.formats.model_file import (
    ArchType,
    HiddenAct,
    ModelFileWriter,
    ModelSpec,
    RopeType,
    tensor_layout,
)
from distributed_llama_tpu.quants import FloatType


def tiny_spec(**overrides) -> ModelSpec:
    """A CPU-friendly llama spec; override any field (seq_len, dims, ...)."""
    defaults = dict(
        arch_type=ArchType.LLAMA,
        dim=32,
        hidden_dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        vocab_size=64,
        seq_len=24,
        hidden_act=HiddenAct.SILU,
        rope_theta=10000.0,
        rope_type=RopeType.UNKNOWN,
        weights_float_type=FloatType.F32,
    )
    defaults.update(overrides)
    return ModelSpec(**defaults)


def random_tensors(spec: ModelSpec, seed: int = 0) -> dict[str, np.ndarray]:
    """Random weights keyed by the `.m` layout names, shaped [d_out, d_in]."""
    rng = np.random.RandomState(seed)
    out: dict[str, np.ndarray] = {}
    for e in tensor_layout(spec):
        if e.name.startswith("rms") or ".rms" in e.name:
            t = 1.0 + 0.1 * rng.randn(*e.shape)
        else:
            t = rng.randn(*e.shape) / np.sqrt(e.shape[-1])
        out[e.name] = t.astype(np.float32)
    return out


def write_model_file(path: str, spec: ModelSpec, tensors: dict[str, np.ndarray]) -> None:
    with open(path, "wb") as f:
        w = ModelFileWriter(f, spec)
        for e in w.remaining():
            w.write_tensor(tensors[e.name], e.name)


def write_synthetic_model(path: str, spec: ModelSpec, seed: int = 0) -> str:
    """One-call helper: random weights for ``spec`` written to ``path``."""
    write_model_file(path, spec, random_tensors(spec, seed=seed))
    return path


# the tiniest template the ChatTemplate sniffer classifies as CHATML
# (tokenizer.detect_chat_template matches on the "<|im_start|>" substring)
SYNTHETIC_CHAT_TEMPLATE = (
    "{{bos_token}}{% for m in messages %}<|im_start|>...{% endfor %}"
)


def synthetic_tokenizer_data():
    """A sentencepiece-style synthetic vocab with full byte fallback:
    <unk>/<s>/</s>, 256 byte tokens, a few merge-scored words — every
    string encodes (1 token per byte for novel text), so synthetic prompts
    need no real tokenizer. The chatml template makes it chat-servable:
    the one shared tokenizer behind the loadgen self-host server
    (loadgen/selfhost.py) and CI-scale serving smokes."""
    from distributed_llama_tpu.formats.tokenizer_file import TokenizerData

    vocab: list[bytes] = [b"<unk>", b"<s>", b"</s>"]
    scores: list[float] = [0.0, 0.0, 0.0]
    for b in range(256):
        vocab.append(f"<0x{b:02X}>".encode())
        scores.append(0.0)
    for tok, score in (
        (b" ", -1.0), (b"h", -2.0), (b"e", -2.0), (b"l", -2.0),
        (b"o", -2.0), (b"he", -3.0), (b"ll", -4.0), (b"hell", -5.0),
        (b"hello", -6.0), (b" hello", -7.0), (b"w", -2.0), (b"r", -2.0),
        (b"d", -2.0), (b"wo", -3.0), (b"wor", -4.0), (b"worl", -5.0),
        (b"world", -6.5), (b" world", -7.5),
    ):
        vocab.append(tok)
        scores.append(score)
    return TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=2,
        chat_template=SYNTHETIC_CHAT_TEMPLATE,
    )
