"""Binary file formats: `.m` model files and `.t` tokenizer files.

Byte-compatible with the reference formats so converted models are
interchangeable (reference: src/transformer.cpp:12-148, src/tokenizer.cpp:39-148,
converter/writer.py:109-143, converter/tokenizer-writer.py).
"""

from distributed_llama_tpu.formats.model_file import (  # noqa: F401
    ArchType,
    HiddenAct,
    ModelSpec,
    ModelFileReader,
    ModelFileWriter,
    RopeType,
    read_spec,
    tensor_layout,
)
from distributed_llama_tpu.formats.tokenizer_file import (  # noqa: F401
    TokenizerData,
    read_tokenizer_file,
    write_tokenizer_file,
)
