"""`.m` model file format: header + flat tensor stream.

Layout (reference: src/transformer.cpp:12-148 for the reader,
converter/writer.py:109-143 for the writer):

  int32 magic = 0xA00ABCD
  int32 header_size            # bytes, including magic and this field
  (int32 key, int32 value) *   # TransformerHeaderKey pairs
  tensor bytes ...             # fixed order, see tensor_layout()

A legacy fixed-struct header (magic 0xABCD00/0xABCD01) is also supported
(reference: src/transformer.cpp:28-43).

Tensor order (reference: src/transformer.cpp:479-540 Transformer::loadRoot):

  embedding (F32) [vocab, dim]
  per layer:
    q [dim, dim], k [kv_dim, dim], v [kv_dim, dim], wo [dim, dim]
    if moe:  router [n_experts, dim];
             per expert: up [hidden, dim], gate [hidden, dim], down [dim, hidden]
    else:    gate/w1 [hidden, dim], down/w2 [dim, hidden], up/w3 [hidden, dim]
    rms_att (F32) [dim], rms_ffn (F32) [dim]
    if grok1: rms_moe (F32) [dim], rms_ffn2 (F32) [dim]
  rms_final (F32) [dim]
  wcls [vocab, dim]

All matrices are row-major [d_out, d_in] — a matmul computes y = W @ x.
Q/K projections are stored pre-permuted for interleaved-pair rope
(reference: converter/convert-hf.py:12-15).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
from typing import BinaryIO, Iterator

import numpy as np

from distributed_llama_tpu.quants import FloatType, deserialize_tensor, serialize_tensor, tensor_bytes

MAGIC_KV = 0xA00ABCD
LEGACY_MAGICS = (0xABCD00, 0xABCD01)


class ArchType(enum.IntEnum):
    """reference: src/transformer.hpp:44-48"""

    LLAMA = 0xABCD00
    GROK1 = 0xABCD01
    MIXTRAL = 0xABCD02


class HiddenAct(enum.IntEnum):
    """reference: src/transformer.hpp:50-53"""

    GELU = 0
    SILU = 1


class RopeType(enum.IntEnum):
    """reference: src/transformer.hpp:55-60"""

    UNKNOWN = -1
    LLAMA = 0
    FALCON = 1
    LLAMA3_1 = 2


class HeaderKey(enum.IntEnum):
    """reference: src/transformer.hpp:10-30"""

    VERSION = 0
    ARCH_TYPE = 1
    DIM = 2
    HIDDEN_DIM = 3
    N_LAYERS = 4
    N_HEADS = 5
    N_KV_HEADS = 6
    N_EXPERTS = 7
    N_ACTIVE_EXPERTS = 8
    VOCAB_SIZE = 9
    SEQ_LEN = 10
    HIDDEN_ACT = 11
    ROPE_THETA = 12
    WEIGHTS_FLOAT_TYPE = 13
    ROPE_SCALING_FACTOR = 14
    ROPE_SCALING_LOW_FREQ_FACTOR = 15
    ROPE_SCALING_HIGH_FREQ_FACTORY = 16
    ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
    ROPE_TYPE = 18


@dataclasses.dataclass
class ModelSpec:
    """Parsed model header ≈ the reference's TransformerSpec
    (reference: src/transformer.hpp:62-90)."""

    arch_type: ArchType
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    n_experts: int = 0
    n_active_experts: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_theta: float = 10000.0
    rope_type: RopeType = RopeType.UNKNOWN
    rope_scaling_factor: float = 0.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    weights_float_type: FloatType = FloatType.Q40
    version: int = 0
    header_size: int = 0
    file_size: int = 0
    orig_seq_len: int = 0

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        # reference: src/transformer.cpp:103-104
        return (self.dim * self.n_kv_heads) // self.n_heads

    def resolved_rope_type(self) -> RopeType:
        """Default rope by arch when the header has none
        (reference: src/transformer.cpp:91-99)."""
        if self.rope_type != RopeType.UNKNOWN:
            return self.rope_type
        if self.arch_type == ArchType.LLAMA:
            return RopeType.LLAMA
        return RopeType.FALCON

    def clamp_seq_len(self, max_seq_len: int | None) -> "ModelSpec":
        """Apply the `--max-seq-len` clamp (reference: src/transformer.cpp:100-103)."""
        spec = dataclasses.replace(self)
        spec.orig_seq_len = self.seq_len if self.orig_seq_len == 0 else self.orig_seq_len
        if max_seq_len and spec.seq_len > max_seq_len:
            spec.seq_len = max_seq_len
        return spec


def _header_pairs(spec: ModelSpec) -> list[tuple[int, int]]:
    pairs = [
        (HeaderKey.VERSION, spec.version),
        (HeaderKey.ARCH_TYPE, int(spec.arch_type)),
        (HeaderKey.DIM, spec.dim),
        (HeaderKey.HIDDEN_DIM, spec.hidden_dim),
        (HeaderKey.N_LAYERS, spec.n_layers),
        (HeaderKey.N_HEADS, spec.n_heads),
        (HeaderKey.N_KV_HEADS, spec.n_kv_heads),
        (HeaderKey.N_EXPERTS, spec.n_experts),
        (HeaderKey.N_ACTIVE_EXPERTS, spec.n_active_experts),
        (HeaderKey.VOCAB_SIZE, spec.vocab_size),
        (HeaderKey.SEQ_LEN, spec.seq_len),
        (HeaderKey.HIDDEN_ACT, int(spec.hidden_act)),
        (HeaderKey.ROPE_THETA, int(spec.rope_theta)),
        (HeaderKey.WEIGHTS_FLOAT_TYPE, int(spec.weights_float_type)),
    ]
    if spec.rope_type != RopeType.UNKNOWN:
        pairs.append((HeaderKey.ROPE_TYPE, int(spec.rope_type)))
    if spec.rope_scaling_factor:
        # header values are int32 — the reference converter truncates the float
        # scaling params to int (reference: converter/convert-hf.py:190-196)
        pairs += [
            (HeaderKey.ROPE_SCALING_FACTOR, int(spec.rope_scaling_factor)),
            (HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR, int(spec.rope_scaling_low_freq_factor)),
            (HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTORY, int(spec.rope_scaling_high_freq_factor)),
            (HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN, spec.rope_scaling_orig_max_seq_len),
        ]
    return pairs


def write_header(f: BinaryIO, spec: ModelSpec) -> int:
    """reference: converter/writer.py:109-143 (header_size = 8 + kv bytes)."""
    pairs = _header_pairs(spec)
    data = b"".join(struct.pack("<ii", int(k), int(v)) for k, v in pairs)
    header_size = 8 + len(data)
    f.write(struct.pack("<i", MAGIC_KV))
    f.write(struct.pack("<i", header_size))
    f.write(data)
    return header_size


def read_spec(path: str, weights_float_type: FloatType | None = None) -> ModelSpec:
    """Parse the `.m` header (reference: src/transformer.cpp:12-148).

    ``weights_float_type`` must be given for legacy-magic files, whose header
    has no dtype field — mirroring the reference's CLI-supplied
    `--weights-float-type` (reference: src/transformer.cpp:28-43,
    src/app.cpp:141-143)."""
    import os

    fields: dict = dict(
        hidden_act=HiddenAct.SILU,
        rope_type=RopeType.UNKNOWN,
        rope_theta=10000.0,
        n_experts=0,
        n_active_experts=0,
    )
    with open(path, "rb") as f:
        (magic,) = struct.unpack("<i", f.read(4))
        if magic in LEGACY_MAGICS:
            vals = struct.unpack("<9i", f.read(36))
            (
                fields["dim"],
                fields["hidden_dim"],
                fields["n_layers"],
                fields["n_heads"],
                fields["n_kv_heads"],
                fields["n_experts"],
                fields["n_active_experts"],
                fields["vocab_size"],
                fields["seq_len"],
            ) = vals
            fields["arch_type"] = ArchType(magic)
            fields["header_size"] = 4 + 36
            fields["weights_float_type"] = (
                None if weights_float_type is None else int(weights_float_type)
            )
        elif magic == MAGIC_KV:
            (header_size,) = struct.unpack("<i", f.read(4))
            n_ints = (header_size - 8) // 4
            raw = struct.unpack(f"<{n_ints}i", f.read(n_ints * 4))
            fields["header_size"] = header_size
            key_map = {
                HeaderKey.VERSION: "version",
                HeaderKey.ARCH_TYPE: "arch_type",
                HeaderKey.DIM: "dim",
                HeaderKey.HIDDEN_DIM: "hidden_dim",
                HeaderKey.N_LAYERS: "n_layers",
                HeaderKey.N_HEADS: "n_heads",
                HeaderKey.N_KV_HEADS: "n_kv_heads",
                HeaderKey.N_EXPERTS: "n_experts",
                HeaderKey.N_ACTIVE_EXPERTS: "n_active_experts",
                HeaderKey.VOCAB_SIZE: "vocab_size",
                HeaderKey.SEQ_LEN: "seq_len",
                HeaderKey.HIDDEN_ACT: "hidden_act",
                HeaderKey.ROPE_THETA: "rope_theta",
                HeaderKey.WEIGHTS_FLOAT_TYPE: "weights_float_type",
                HeaderKey.ROPE_SCALING_FACTOR: "rope_scaling_factor",
                HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR: "rope_scaling_low_freq_factor",
                HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTORY: "rope_scaling_high_freq_factor",
                HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN: "rope_scaling_orig_max_seq_len",
                HeaderKey.ROPE_TYPE: "rope_type",
            }
            for i in range(0, n_ints, 2):
                key, value = raw[i], raw[i + 1]
                try:
                    name = key_map[HeaderKey(key)]
                except ValueError:
                    raise ValueError(f"unsupported header key: {key}") from None
                fields[name] = value
        else:
            raise ValueError(f"unsupported model file magic: {magic & 0xFFFFFFFF:#x}")
        fields["file_size"] = os.fstat(f.fileno()).st_size

    fields["arch_type"] = ArchType(fields["arch_type"])
    fields["hidden_act"] = HiddenAct(fields["hidden_act"])
    fields["rope_type"] = RopeType(fields.get("rope_type", -1))
    fields["rope_theta"] = float(fields["rope_theta"])
    if fields.get("weights_float_type") is None:
        raise ValueError("legacy header does not carry a weights float type; pass it explicitly")
    fields["weights_float_type"] = FloatType(fields["weights_float_type"])
    fields["orig_seq_len"] = fields["seq_len"]
    return ModelSpec(**fields)


@dataclasses.dataclass(frozen=True)
class TensorEntry:
    name: str
    shape: tuple[int, ...]
    float_type: FloatType
    offset: int  # absolute byte offset in file
    nbytes: int

    @property
    def n_values(self) -> int:
        return int(np.prod(self.shape))


def tensor_layout(spec: ModelSpec) -> list[TensorEntry]:
    """The fixed tensor order of the `.m` stream
    (reference: src/transformer.cpp:479-540)."""
    wt = spec.weights_float_type
    dim, hidden, kv_dim, vocab = spec.dim, spec.hidden_dim, spec.kv_dim, spec.vocab_size
    entries: list[TensorEntry] = []
    offset = spec.header_size

    def add(name: str, shape: tuple[int, ...], ft: FloatType):
        nonlocal offset
        nbytes = tensor_bytes(ft, int(np.prod(shape)))
        entries.append(TensorEntry(name, shape, ft, offset, nbytes))
        offset += nbytes

    add("embedding", (vocab, dim), FloatType.F32)
    for l in range(spec.n_layers):
        p = f"layers.{l}."
        add(p + "q", (dim, dim), wt)
        add(p + "k", (kv_dim, dim), wt)
        add(p + "v", (kv_dim, dim), wt)
        add(p + "wo", (dim, dim), wt)
        if spec.n_experts > 0:
            add(p + "moe_router", (spec.n_experts, dim), wt)
            for e in range(spec.n_experts):
                ep = f"{p}experts.{e}."
                add(ep + "up", (hidden, dim), wt)
                add(ep + "gate", (hidden, dim), wt)
                add(ep + "down", (dim, hidden), wt)
        else:
            add(p + "gate", (hidden, dim), wt)  # w1
            add(p + "down", (dim, hidden), wt)  # w2
            add(p + "up", (hidden, dim), wt)  # w3
        add(p + "rms_att", (dim,), FloatType.F32)
        add(p + "rms_ffn", (dim,), FloatType.F32)
        if spec.arch_type == ArchType.GROK1:
            add(p + "rms_moe", (dim,), FloatType.F32)
            add(p + "rms_ffn2", (dim,), FloatType.F32)
    add("rms_final", (dim,), FloatType.F32)
    add("wcls", (vocab, dim), wt)
    return entries


class ModelFileReader:
    """mmap-backed random access to the tensors of a `.m` file.

    The reference streams the file sequentially through sockets
    (reference: src/transformer.cpp:432-451); on TPU each host instead reads
    only the byte ranges of its own shards, so this reader exposes per-tensor
    (and per-row-range) random access over a single mmap.
    """

    def __init__(
        self,
        path: str,
        spec: ModelSpec | None = None,
        weights_float_type: FloatType | None = None,
    ):
        self.path = path
        self.spec = spec or read_spec(path, weights_float_type=weights_float_type)
        self.entries = {e.name: e for e in tensor_layout(self.spec)}
        last = max(self.entries.values(), key=lambda e: e.offset)
        expected = last.offset + last.nbytes
        if self.spec.file_size and expected != self.spec.file_size:
            raise ValueError(
                f"model file size mismatch: layout expects {expected} bytes, file has {self.spec.file_size}"
            )
        self._mmap = np.memmap(path, dtype=np.uint8, mode="r")
        self.bytes_read = 0  # logical bytes served (sharded-load accounting)

    def names(self) -> list[str]:
        return list(self.entries)

    def raw(self, name: str) -> np.ndarray:
        e = self.entries[name]
        self.bytes_read += e.nbytes
        return self._mmap[e.offset : e.offset + e.nbytes]

    def raw_rows(self, name: str, row_start: int, row_end: int) -> np.ndarray:
        """Raw bytes of a contiguous row (output-dim) range — the exact-repack
        shard read for output-sharded Q40 matrices (the read-time analogue of
        RowMatmulSlice, reference: src/commands.cpp:22-43)."""
        e = self.entries[name]
        n = e.shape[1]
        row_bytes = tensor_bytes(e.float_type, n)
        start = e.offset + row_start * row_bytes
        nbytes = (row_end - row_start) * row_bytes
        self.bytes_read += nbytes
        return self._mmap[start : start + nbytes]

    def raw_row_blocks(self, name: str, col_start: int, col_end: int) -> np.ndarray:
        """Raw bytes of a column (input-dim) range of every row, sliced on
        quant-block boundaries — the shard read for input-sharded Q40
        matrices (ColMatmulSlice applied at read time, reference:
        src/commands.cpp:57-73). Returns [d_out, col_bytes] bytes."""
        from distributed_llama_tpu.quants import QK

        e = self.entries[name]
        d_out, d_in = e.shape
        if col_start % QK or col_end % QK:
            raise ValueError(f"column range ({col_start},{col_end}) not {QK}-aligned")
        row_bytes = tensor_bytes(e.float_type, d_in)
        lo = tensor_bytes(e.float_type, col_start)
        hi = tensor_bytes(e.float_type, col_end)
        rows = self._mmap[e.offset : e.offset + e.nbytes].reshape(d_out, row_bytes)
        out = np.ascontiguousarray(rows[:, lo:hi])
        self.bytes_read += out.nbytes
        return out

    def tensor(self, name: str) -> np.ndarray:
        """Dequantized float32 tensor in its logical shape."""
        e = self.entries[name]
        flat = deserialize_tensor(self.raw(name), e.float_type, e.n_values)
        return flat.reshape(e.shape)

    def tensor_rows(self, name: str, row_start: int, row_end: int) -> np.ndarray:
        """Read a contiguous row range without touching the rest of the tensor.

        This is the sharded-load path: the byte math mirrors the reference's
        RowMatmulSlice offset computation (reference: src/commands.cpp:22-43)
        but is applied at read time on each host instead of at scatter time on
        a root node.
        """
        e = self.entries[name]
        if len(e.shape) != 2:
            raise ValueError(f"tensor_rows on non-matrix {name}")
        n = e.shape[1]
        row_bytes = tensor_bytes(e.float_type, n)
        start = e.offset + row_start * row_bytes
        nrows = row_end - row_start
        buf = self._mmap[start : start + nrows * row_bytes]
        self.bytes_read += nrows * row_bytes
        flat = deserialize_tensor(buf, e.float_type, nrows * n)
        return flat.reshape(nrows, n)

    def tensor_cols(self, name: str, col_start: int, col_end: int) -> np.ndarray:
        """Read a column (input-dim) range of every row — the input-sharded
        analogue of :meth:`tensor_rows` (ColMatmulSlice applied at read
        time). Works for every on-disk dtype: block formats (Q40/Q80) slice
        on quant-block boundaries via :meth:`raw_row_blocks` when the range
        is aligned, else fall back to decoding whole rows (correct, just
        full-row file traffic — counted honestly in ``bytes_read``).
        Returns f32 [d_out, cols]."""
        from distributed_llama_tpu.quants import QK

        e = self.entries[name]
        if len(e.shape) != 2:
            raise ValueError(f"tensor_cols on non-matrix {name}")
        d_out, d_in = e.shape
        ncols = col_end - col_start
        if e.float_type in (FloatType.Q40, FloatType.Q80):
            if col_start % QK == 0 and col_end % QK == 0:
                buf = self.raw_row_blocks(name, col_start, col_end)
                flat = deserialize_tensor(buf.reshape(-1), e.float_type, d_out * ncols)
                return flat.reshape(d_out, ncols)
            return self.tensor(name)[:, col_start:col_end]
        row_bytes = tensor_bytes(e.float_type, d_in)
        lo = tensor_bytes(e.float_type, col_start)
        hi = tensor_bytes(e.float_type, col_end)
        rows = self._mmap[e.offset : e.offset + e.nbytes].reshape(d_out, row_bytes)
        buf = np.ascontiguousarray(rows[:, lo:hi])
        self.bytes_read += buf.nbytes
        flat = deserialize_tensor(buf.reshape(-1), e.float_type, d_out * ncols)
        return flat.reshape(d_out, ncols)

    def close(self):
        del self._mmap


class ModelFileWriter:
    """Sequential `.m` writer used by the converter toolchain
    (reference: converter/writer.py)."""

    def __init__(self, f: BinaryIO, spec: ModelSpec):
        self.f = f
        self.spec = spec
        self.header_size = write_header(f, spec)
        self._layout = tensor_layout(
            dataclasses.replace(spec, header_size=self.header_size)
        )
        self._next = 0

    def write_tensor(self, array: np.ndarray, name: str | None = None) -> TensorEntry:
        """Write the next tensor in layout order; `name` is checked if given."""
        entry = self._layout[self._next]
        if name is not None and name != entry.name:
            raise ValueError(f"expected tensor {entry.name!r}, got {name!r}")
        if tuple(array.shape) != entry.shape and array.size != entry.n_values:
            raise ValueError(
                f"tensor {entry.name}: shape {array.shape} incompatible with {entry.shape}"
            )
        self.f.write(serialize_tensor(array, entry.float_type))
        self._next += 1
        return entry

    def expected(self) -> TensorEntry:
        return self._layout[self._next]

    def remaining(self) -> Iterator[TensorEntry]:
        return iter(self._layout[self._next :])

    def finish(self):
        if self._next != len(self._layout):
            missing = [e.name for e in self._layout[self._next :]]
            raise ValueError(f"model file incomplete, missing tensors: {missing[:5]}...")
