"""`.t` tokenizer file format.

Layout (reference: src/tokenizer.cpp:39-148 reader,
converter/tokenizer-writer.py writer):

  int32 magic = 0x567124
  int32 header_size                       # 8 + kv bytes
  (int32 key, int32 value) *              # TokenizerHeaderKey pairs
  chat_template bytes (if announced)      # utf-8 jinja template
  chat_stop bytes (if announced)          # extra stop string
  per token: float32 score, uint32 len, len bytes

The legacy fixed header (magic 0x567123) is also readable
(reference: src/tokenizer.hpp:16-22).
"""

from __future__ import annotations

import dataclasses
import enum
import struct
from typing import BinaryIO

MAGIC_KV = 0x567124
MAGIC_OLD = 0x567123


class TokHeaderKey(enum.IntEnum):
    """reference: src/tokenizer.hpp:24-34"""

    VERSION = 0
    VOCAB_SIZE = 1
    MAX_TOKEN_LENGTH = 2
    BOS_ID = 3
    EOS_ID = 4
    PAD_ID = 5
    CHAT_EOS_ID = 6
    CHAT_TEMPLATE = 7
    CHAT_STOP = 8


@dataclasses.dataclass
class TokenizerData:
    vocab: list[bytes]
    scores: list[float]
    bos_id: int = -1
    eos_id: int = -1
    chat_eos_id: int = -1
    pad_id: int = -1
    chat_template: str | None = None
    chat_stop: str | None = None

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def max_token_length(self) -> int:
        return max((len(t) for t in self.vocab), default=0)


def read_tokenizer_file(path: str) -> TokenizerData:
    with open(path, "rb") as f:
        (magic,) = struct.unpack("<i", f.read(4))
        chat_template_len = -1
        chat_stop_len = -1
        bos_id = eos_id = chat_eos_id = pad_id = -1
        if magic == MAGIC_OLD:
            vocab_size, max_token_length, bos_id, eos_id, pad_id = struct.unpack(
                "<IIiii", f.read(20)
            )
        elif magic == MAGIC_KV:
            (header_size,) = struct.unpack("<i", f.read(4))
            n_ints = (header_size - 8) // 4
            raw = struct.unpack(f"<{n_ints}i", f.read(n_ints * 4))
            version = -1
            vocab_size = 0
            for i in range(0, n_ints, 2):
                key, value = raw[i], raw[i + 1]
                if key == TokHeaderKey.VERSION:
                    version = value
                elif key == TokHeaderKey.VOCAB_SIZE:
                    vocab_size = value
                elif key == TokHeaderKey.MAX_TOKEN_LENGTH:
                    pass  # recomputed from the vocab
                elif key == TokHeaderKey.BOS_ID:
                    bos_id = value
                elif key == TokHeaderKey.EOS_ID:
                    eos_id = value
                elif key == TokHeaderKey.CHAT_EOS_ID:
                    chat_eos_id = value
                elif key == TokHeaderKey.CHAT_TEMPLATE:
                    chat_template_len = value
                elif key == TokHeaderKey.CHAT_STOP:
                    chat_stop_len = value
                elif key == TokHeaderKey.PAD_ID:
                    pad_id = value
                else:
                    raise ValueError(f"invalid tokenizer header key: {key}")
            if version != 1:
                raise ValueError("old tokenizer version, please regenerate the tokenizer")
        else:
            raise ValueError(f"invalid tokenizer file magic: {magic & 0xFFFFFFFF:#x}")

        chat_template = None
        chat_stop = None
        if chat_template_len > 0:
            chat_template = f.read(chat_template_len).decode("utf-8")
        if chat_stop_len > 0:
            chat_stop = f.read(chat_stop_len).decode("utf-8")

        vocab: list[bytes] = []
        scores: list[float] = []
        for _ in range(vocab_size):
            score, length = struct.unpack("<fI", f.read(8))
            vocab.append(f.read(length))
            scores.append(score)

    return TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        eos_id=eos_id,
        chat_eos_id=chat_eos_id,
        pad_id=pad_id,
        chat_template=chat_template,
        chat_stop=chat_stop,
    )


def write_tokenizer_file(f: BinaryIO, data: TokenizerData) -> None:
    """reference: converter/tokenizer-writer.py:3-59"""
    if data.bos_id < 0 or data.eos_id < 0:
        raise ValueError("tokenizer requires bos_id and eos_id")
    template_bytes = data.chat_template.encode("utf-8") if data.chat_template else None
    stop_bytes = data.chat_stop.encode("utf-8") if data.chat_stop else None

    pairs: list[tuple[int, int]] = [
        (TokHeaderKey.VERSION, 1),
        (TokHeaderKey.VOCAB_SIZE, data.vocab_size),
        (TokHeaderKey.MAX_TOKEN_LENGTH, data.max_token_length),
        (TokHeaderKey.BOS_ID, data.bos_id),
        (TokHeaderKey.EOS_ID, data.eos_id),
    ]
    if data.pad_id >= 0:
        pairs.append((TokHeaderKey.PAD_ID, data.pad_id))
    if data.chat_eos_id >= 0:
        pairs.append((TokHeaderKey.CHAT_EOS_ID, data.chat_eos_id))
    if template_bytes:
        pairs.append((TokHeaderKey.CHAT_TEMPLATE, len(template_bytes)))
    if stop_bytes:
        pairs.append((TokHeaderKey.CHAT_STOP, len(stop_bytes)))

    kv = b"".join(struct.pack("<ii", int(k), int(v)) for k, v in pairs)
    f.write(struct.pack("<i", MAGIC_KV))
    f.write(struct.pack("<i", 8 + len(kv)))
    f.write(kv)
    if template_bytes:
        f.write(template_bytes)
    if stop_bytes:
        f.write(stop_bytes)
    for token, score in zip(data.vocab, data.scores):
        if len(token) == 0:
            raise ValueError("empty token in vocab")
        f.write(struct.pack("<fI", score, len(token)))
        f.write(token)
