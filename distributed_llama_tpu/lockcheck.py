"""Runtime lock-order witness (ISSUE 20) — the dynamic half of LCK-003.

The static rule (analysis/rules/locks.py) proves the LEXICAL acquisition
graph respects the hierarchy declared in pyproject's
``[tool.dllama.analysis.locks]`` table, but the orders that actually
deadlock in this codebase flow through edges the AST cannot see: the
scheduler's ``health_hook`` callback into the pool, the restart
supervisor and canary threads, fault-injection paths that fire once per
thousand requests. This module witnesses those at runtime: every named
lock construction site in the package calls :func:`make_lock` /
:func:`make_rlock` / :func:`make_condition` with its "Class._attr" name,
and when the witness is armed each acquisition is checked against a
per-thread stack of held ranks — acquiring a rank ≤ any held rank (on a
different lock) is a violation, as is a blocking re-acquire of a plain
(non-reentrant) Lock by its own holder (a guaranteed self-deadlock,
reported BEFORE the thread hangs).

Off by default and zero-cost when off: the factories return plain
``threading`` primitives unless armed, so the hot path never pays for the
bookkeeping. Arming:

* ``DLT_LOCK_CHECK=1`` (or ``raise``) — violations raise
  :class:`LockOrderViolation` at the acquisition site (and are recorded).
* ``DLT_LOCK_CHECK=warn`` — violations are only recorded; read them with
  :func:`violations` (the chaos tests assert the ledger is empty after a
  replica-kill storm).
* :func:`configure` — explicit mode/ranks override for tests.

The mode is sampled at CONSTRUCTION time (the env var must be set before
the pool/scheduler is built — tests/conftest or the CI step export it),
and the rank table loads lazily from the same pyproject the analyzer
reads, so the static rule, the witness and the docs can never drift.

``Condition.wait`` is handled faithfully: waiting releases the lock, so
the witness pops its entries for the wait and re-pushes them on wakeup
WITHOUT an order check (the wakeup re-acquire is wakeup-ordered — the
hazard the check targets is nesting, not reclaiming).
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "LockOrderViolation",
    "configure",
    "enabled",
    "make_condition",
    "make_lock",
    "make_rlock",
    "reset",
    "violations",
]


class LockOrderViolation(RuntimeError):
    """A runtime lock acquisition violated the declared hierarchy."""


_tls = threading.local()  # .held: list[(name, rank, id(lock-obj))]
_ledger_lock = threading.Lock()
_ledger: list[str] = []
_ranks_override: dict[str, int] | None = None
_ranks_cache: dict[str, int] | None = None
_mode_override: str | None = None  # "raise" | "warn" | "off"


def configure(
    ranks: dict[str, int] | None = None, mode: str | None = None
) -> None:
    """Test hook: pin the rank table and/or mode ("raise"/"warn"/"off")
    instead of reading pyproject / the environment. ``None`` restores the
    default source for that setting."""
    global _ranks_override, _mode_override, _ranks_cache
    _ranks_override = dict(ranks) if ranks is not None else None
    _mode_override = mode
    _ranks_cache = None


def _active_mode() -> str:
    if _mode_override is not None:
        return _mode_override
    v = os.environ.get("DLT_LOCK_CHECK", "").strip().lower()
    if v in ("1", "true", "on", "raise"):
        return "raise"
    if v == "warn":
        return "warn"
    return "off"


def enabled() -> bool:
    return _active_mode() != "off"


def _rank_table() -> dict[str, int]:
    global _ranks_cache
    if _ranks_override is not None:
        return _ranks_override
    if _ranks_cache is None:
        try:
            from .analysis.config import load_config

            cfg = load_config(start=os.path.dirname(os.path.abspath(__file__)))
            _ranks_cache = dict(cfg.lock_ranks)
        except Exception:
            _ranks_cache = {}
    return _ranks_cache


def violations() -> list[str]:
    """The recorded violations (both modes record before raising)."""
    with _ledger_lock:
        return list(_ledger)


def reset() -> None:
    with _ledger_lock:
        _ledger.clear()


def _held() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _violate(mode: str, message: str) -> None:
    with _ledger_lock:
        _ledger.append(message)
    if mode == "raise":
        raise LockOrderViolation(message)


def _check_order(mode: str, name: str, rank: int, obj_id: int) -> None:
    for held_name, held_rank, held_id in _held():
        if held_id == obj_id:
            continue
        if held_rank >= rank:
            _violate(
                mode,
                f"lock-order inversion: acquiring `{name}` (rank {rank})"
                f" while `{held_name}` (rank {held_rank}) is held — the"
                " declared hierarchy ([tool.dllama.analysis.locks])"
                " requires strictly ascending ranks",
            )


class _WitnessLock:
    """A non-reentrant Lock under the witness. A blocking re-acquire by
    the holding thread is reported as a violation INSTEAD of deadlocking
    the test run."""

    def __init__(self, name: str, rank: int, mode: str):
        self._name, self._rank, self._mode = name, rank, mode
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        mine = id(self)
        if blocking and any(h[2] == mine for h in held):
            _violate(
                self._mode,
                f"self-deadlock: `{self._name}` re-acquired (blocking) by"
                " the thread that already holds it — threading.Lock is"
                " not reentrant",
            )
        _check_order(self._mode, self._name, self._rank, mine)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held.append((self._name, self._rank, mine))
        return ok

    def release(self) -> None:
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][2] == id(self):
                del held[i]
                break

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<_WitnessLock {self._name} rank={self._rank}>"


class _WitnessRLock:
    """A reentrant lock under the witness; also the lock a witnessed
    Condition is built over. Implements the ``_release_save`` /
    ``_acquire_restore`` / ``_is_owned`` protocol ``threading.Condition``
    uses, popping the witness entries across a ``wait`` (which releases
    the lock) and re-pushing them on wakeup without an order check."""

    def __init__(self, name: str, rank: int, mode: str):
        self._name, self._rank, self._mode = name, rank, mode
        self._inner = threading.RLock()

    def _mine(self) -> int:
        return sum(1 for h in _held() if h[2] == id(self))

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._mine() == 0:
            _check_order(self._mode, self._name, self._rank, id(self))
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _held().append((self._name, self._rank, id(self)))
        return ok

    def release(self) -> None:
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][2] == id(self):
                del held[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- the Condition integration protocol -----------------------------

    def _release_save(self):
        state = self._inner._release_save()
        held = _held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][2] == id(self):
                del held[i]
                n += 1
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        self._inner._acquire_restore(state)
        held = _held()
        for _ in range(n):
            # wakeup re-acquire: exempt from the order check by design
            held.append((self._name, self._rank, id(self)))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def __repr__(self) -> str:
        return f"<_WitnessRLock {self._name} rank={self._rank}>"


def make_lock(name: str) -> threading.Lock:
    """A ``threading.Lock`` for the construction site ``name``
    ("Class._attr"); witness-wrapped when the checker is armed AND the
    name is ranked in the declared hierarchy."""
    mode = _active_mode()
    if mode == "off":
        return threading.Lock()
    rank = _rank_table().get(name)
    if rank is None:
        return threading.Lock()
    return _WitnessLock(name, rank, mode)


def make_rlock(name: str) -> threading.RLock:
    mode = _active_mode()
    if mode == "off":
        return threading.RLock()
    rank = _rank_table().get(name)
    if rank is None:
        return threading.RLock()
    return _WitnessRLock(name, rank, mode)


def make_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` whose underlying (reentrant) lock is
    witnessed — ``with cond:`` / ``cond.acquire`` check the hierarchy,
    ``cond.wait`` releases and reclaims without a spurious check."""
    mode = _active_mode()
    if mode == "off":
        return threading.Condition()
    rank = _rank_table().get(name)
    if rank is None:
        return threading.Condition()
    return threading.Condition(_WitnessRLock(name, rank, mode))
