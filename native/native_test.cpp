// Standalone C++ test binary for the native host library — the same test
// shape as the reference's funcs-test/quants-test mains (standalone
// executables, exit(1) on failure, reference: src/quants-test.cpp). Built
// and run under AddressSanitizer in CI (the reference ships no sanitizer
// lane at all — SURVEY.md §5).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
void q40_dequant_f32(const uint8_t* blocks, int64_t n_blocks, float* out);
void q40_repack_tpu(const uint8_t* blocks, int64_t d_out, int64_t d_in,
                    int64_t n_pad, uint8_t* packed, float* scales_t);
void* bpe_new(const uint8_t* vocab_bytes, const int64_t* offsets,
              const float* scores, int32_t n_vocab);
void bpe_free(void* handle);
int32_t bpe_encode(void* handle, const uint8_t* text, int64_t len, int32_t* out);
}

#define CHECK(cond)                                                    \
    do {                                                               \
        if (!(cond)) {                                                 \
            std::fprintf(stderr, "FAILED: %s (%s:%d)\n", #cond,        \
                         __FILE__, __LINE__);                          \
            std::exit(1);                                              \
        }                                                              \
    } while (0)

namespace {

constexpr int QK = 32;
constexpr int BLOCK_BYTES = 2 + QK / 2;

// minimal f32 -> f16 for building test blocks (round-to-nearest-even not
// required: we only use exactly-representable scales)
uint16_t f32_to_f16_exact(float f) {
    uint32_t bits;
    std::memcpy(&bits, &f, 4);
    uint32_t sign = (bits >> 16) & 0x8000;
    int32_t exp = (int32_t)((bits >> 23) & 0xFF) - 127 + 15;
    uint32_t mant = (bits >> 13) & 0x3FF;
    if (f == 0.0f) return (uint16_t)sign;
    CHECK(exp > 0 && exp < 31);  // test scales stay in normal f16 range
    return (uint16_t)(sign | ((uint32_t)exp << 10) | mant);
}

// one Q40 block: scale then 16 nibble bytes (value j low, value j+16 high)
void write_block(uint8_t* dst, float scale, const int* vals /* 32, biased 0..15 */) {
    uint16_t h = f32_to_f16_exact(scale);
    std::memcpy(dst, &h, 2);
    for (int j = 0; j < QK / 2; j++) {
        dst[2 + j] = (uint8_t)(vals[j] | (vals[j + QK / 2] << 4));
    }
}

void test_dequant() {
    // two blocks with known scales/values
    std::vector<uint8_t> blocks(2 * BLOCK_BYTES);
    int vals[QK];
    for (int i = 0; i < QK; i++) vals[i] = i % 16;
    write_block(blocks.data(), 0.5f, vals);
    for (int i = 0; i < QK; i++) vals[i] = 15 - i % 16;
    write_block(blocks.data() + BLOCK_BYTES, 2.0f, vals);

    std::vector<float> out(2 * QK);
    q40_dequant_f32(blocks.data(), 2, out.data());
    for (int i = 0; i < QK; i++) {
        CHECK(out[i] == ((i % 16) - 8) * 0.5f);
        CHECK(out[QK + i] == ((15 - i % 16) - 8) * 2.0f);
    }
    std::printf("  dequant: ok\n");
}

void test_repack_half_split() {
    // verify the half-split layout: packed[(v % half) * d_out + r] holds
    // value v of row r, low nibble when v < half. Nibbles stay BIASED
    // (0..15) — the TPU kernel subtracts the +8 bias as a rank-reduced
    // correction, not at repack time
    const int64_t d_out = 4, d_in = 2 * QK, n_pad = 64;  // half = 32: block 0
    const int64_t bpr = d_in / QK;                       // lands in low nibbles,
    std::vector<uint8_t> blocks(d_out * bpr * BLOCK_BYTES);  // block 1 in high
    int vals[QK];
    for (int64_t r = 0; r < d_out; r++) {
        for (int64_t b = 0; b < bpr; b++) {
            for (int i = 0; i < QK; i++) vals[i] = (int)((i + r + 3 * b) % 16);
            write_block(blocks.data() + (r * bpr + b) * BLOCK_BYTES,
                        1.0f + (float)(r + b * d_out), vals);
        }
    }
    const int64_t half = n_pad / 2;
    std::vector<uint8_t> packed(half * d_out, 0);
    std::vector<float> scales(n_pad / QK * d_out, 0.0f);
    q40_repack_tpu(blocks.data(), d_out, d_in, n_pad, packed.data(), scales.data());

    for (int64_t r = 0; r < d_out; r++) {
        CHECK(scales[r] == 1.0f + (float)r);           // block 0 scale row
        CHECK(scales[d_out + r] == 1.0f + (float)(r + d_out));  // block 1
        for (int v = 0; v < (int)d_in; v++) {
            int b = v / QK;
            int expect = (int)((v % QK + r + 3 * b) % 16);  // biased nibble
            uint8_t byte = packed[(v % half) * d_out + r];
            int nib = (v < half) ? (byte & 0xF) : (byte >> 4);
            CHECK(nib == expect);
        }
    }
    std::printf("  repack: ok\n");
}

void test_bpe() {
    // vocab: bytes 'a','b','c', merged token "ab" with the best score
    const char* toks[] = {"a", "b", "c", "ab"};
    float scores[] = {-4.0f, -4.0f, -4.0f, -1.0f};
    std::vector<uint8_t> blob;
    std::vector<int64_t> offsets = {0};
    for (const char* t : toks) {
        for (const char* p = t; *p; p++) blob.push_back((uint8_t)*p);
        offsets.push_back((int64_t)blob.size());
    }
    void* h = bpe_new(blob.data(), offsets.data(), scores, 4);
    CHECK(h != nullptr);
    const char* text = "abcab";
    std::vector<int32_t> out(16);
    int32_t n = bpe_encode(h, (const uint8_t*)text, 5, out.data());
    CHECK(n == 3);
    CHECK(out[0] == 3 && out[1] == 2 && out[2] == 3);  // ab c ab
    bpe_free(h);
    std::printf("  bpe: ok\n");
}

}  // namespace

int main() {
    test_dequant();
    test_repack_half_split();
    test_bpe();
    std::printf("native_test: all ok ✅\n");
    return 0;
}
