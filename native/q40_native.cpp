// Native host-side kernels for the weight-loading path.
//
// The reference does all of this in C++ too (quants.cpp dequant at load,
// commands.cpp splitWeights at scatter); here the hot host paths are the
// Q40 file-block -> TPU-layout repack and bulk dequantization, which for a
// 405B/238GB checkpoint are the difference between minutes and hours on the
// loading host. Exposed as a C ABI for ctypes (no pybind11 dependency).
//
// Layouts:
//   file blocks (reference src/quants.hpp:17-20): per 32 values,
//     2-byte f16 scale + 16 bytes, low nibble = value j, high = value j+16.
//   TPU packed (ops/q40.py pack_q40_tpu): for W stored row-major
//     [d_out, d_in], outputs the HALF-SPLIT form packed[n_pad/2, d_out]
//     (n_pad = padded d_in, zero-scale padding): byte (i, r) holds W^T row i
//     in the low nibble and row i + n_pad/2 in the high nibble, plus
//     scales_t[n_pad/32, d_out]. Half-split pairing lets the TPU kernel
//     contract low/high nibbles against two contiguous windows of x.

#include <cstdint>
#include <cstring>

namespace {

// f16 -> f32 without F16C intrinsics (bit manipulation, handles subnormals)
inline float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000) << 16;
    uint32_t exp = (h >> 10) & 0x1F;
    uint32_t mant = h & 0x3FF;
    uint32_t bits;
    if (exp == 0) {
        if (mant == 0) {
            bits = sign;
        } else {
            // subnormal: normalize
            int shift = 0;
            while (!(mant & 0x400)) { mant <<= 1; shift++; }
            mant &= 0x3FF;
            bits = sign | ((112 - shift) << 23) | (mant << 13);
        }
    } else if (exp == 0x1F) {
        bits = sign | 0x7F800000 | (mant << 13);
    } else {
        bits = sign | ((exp + 112) << 23) | (mant << 13);
    }
    float f;
    std::memcpy(&f, &bits, 4);
    return f;
}

constexpr int QK = 32;
constexpr int BLOCK_BYTES = 2 + QK / 2;  // f16 scale + 16 nibble bytes

}  // namespace

extern "C" {

// Dequantize n_blocks Q40 file blocks to f32 (row-major stream).
// out must hold n_blocks * 32 floats.
void q40_dequant_f32(const uint8_t* blocks, int64_t n_blocks, float* out) {
    for (int64_t b = 0; b < n_blocks; b++) {
        const uint8_t* blk = blocks + b * BLOCK_BYTES;
        uint16_t h;
        std::memcpy(&h, blk, 2);
        const float scale = f16_to_f32(h);
        const uint8_t* qs = blk + 2;
        float* o = out + b * QK;
        for (int j = 0; j < QK / 2; j++) {
            o[j] = (float)((int)(qs[j] & 0xF) - 8) * scale;
            o[j + QK / 2] = (float)((int)(qs[j] >> 4) - 8) * scale;
        }
    }
}

// Repack a Q40 tensor from file block order into the half-split TPU layout.
//   blocks:   [d_out * (d_in/32)] file blocks, row-major per output row
//   n_pad:    padded input dim (multiple of 64, >= d_in); rows d_in..n_pad-1
//             carry zero scales so their nibble content never matters, but
//             packed MUST be zero-initialized (nibbles are OR-ed in)
//   packed:   out uint8 [n_pad/2, d_out]
//   scales_t: out f32 [n_pad/32, d_out] — MUST be zero-initialized (padding
//             scale rows stay 0)
// Tiled over d_out to keep the transposed writes in cache.
void q40_repack_tpu(const uint8_t* blocks, int64_t d_out, int64_t d_in,
                    int64_t n_pad, uint8_t* packed, float* scales_t) {
    const int64_t bpr = d_in / QK;  // blocks per row
    const int64_t half = n_pad / 2;
    const int64_t TILE = 64;
    for (int64_t r0 = 0; r0 < d_out; r0 += TILE) {
        const int64_t r1 = r0 + TILE < d_out ? r0 + TILE : d_out;
        for (int64_t r = r0; r < r1; r++) {
            const uint8_t* row = blocks + r * bpr * BLOCK_BYTES;
            for (int64_t b = 0; b < bpr; b++) {
                const uint8_t* blk = row + b * BLOCK_BYTES;
                uint16_t h;
                std::memcpy(&h, blk, 2);
                scales_t[b * d_out + r] = f16_to_f32(h);
                const uint8_t* qs = blk + 2;
                // value index v within the row: v = b*32 + j (low nibble of
                // qs[j]) or b*32 + 16 + j (high nibble). Output byte at
                // packed[(v % half) * d_out + r]: low nibble if v < half,
                // high nibble otherwise.
                for (int j = 0; j < QK / 2; j++) {
                    const int64_t v_a = b * QK + j;
                    const int64_t v_b = v_a + QK / 2;
                    const uint8_t a_val = qs[j] & 0xF;
                    const uint8_t b_val = qs[j] >> 4;
                    uint8_t* p_a = packed + (v_a % half) * d_out + r;
                    uint8_t* p_b = packed + (v_b % half) * d_out + r;
                    *p_a |= (v_a < half) ? a_val : (uint8_t)(a_val << 4);
                    *p_b |= (v_b < half) ? b_val : (uint8_t)(b_val << 4);
                }
            }
        }
    }
}

}  // extern "C"
