// Native BPE encoder: the host-side tokenize hot path.
//
// Same algorithm as the Python Tokenizer.encode (and the reference's
// src/tokenizer.cpp:170-292): UTF-8 codepoint split with byte fallback (+3),
// then greedy highest-score adjacent-pair merging. The merge loop is
// O(n^2 * lookup); C++ with an open-addressing string map makes multi-KB
// prompts tokenize in microseconds instead of milliseconds.
//
// C ABI for ctypes. A tokenizer handle owns copies of the vocab.

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Bpe {
    std::vector<std::string> vocab;
    std::vector<float> scores;
    std::unordered_map<std::string, int32_t> index;  // first-wins
};

}  // namespace

extern "C" {

// vocab_bytes: concatenated token byte strings; offsets: n+1 prefix offsets.
void* bpe_new(const uint8_t* vocab_bytes, const int64_t* offsets,
              const float* scores, int32_t n) {
    Bpe* b = new Bpe();
    b->vocab.reserve(n);
    b->scores.assign(scores, scores + n);
    for (int32_t i = 0; i < n; i++) {
        b->vocab.emplace_back((const char*)vocab_bytes + offsets[i],
                              (size_t)(offsets[i + 1] - offsets[i]));
        b->index.emplace(b->vocab.back(), i);
    }
    return b;
}

void bpe_free(void* handle) { delete (Bpe*)handle; }

// Encode text to token ids. Returns the token count (<= max_out guaranteed
// by the caller sizing out as len(text) + 1). No BOS/EOS/dummy-prefix —
// the Python wrapper adds those (they are cheap and policy-laden).
int32_t bpe_encode(void* handle, const uint8_t* text, int64_t len,
                   int32_t* out) {
    Bpe* b = (Bpe*)handle;
    std::vector<int32_t> tokens;
    tokens.reserve(len);

    // UTF-8 codepoint split with byte fallback (+3)
    int64_t i = 0;
    std::string piece;
    while (i < len) {
        int64_t j = i + 1;
        while (j < len && (text[j] & 0xC0) == 0x80 && (j - i) < 4) j++;
        piece.assign((const char*)text + i, (size_t)(j - i));
        auto it = b->index.find(piece);
        if (it != b->index.end()) {
            tokens.push_back(it->second);
        } else {
            for (int64_t k = i; k < j; k++) tokens.push_back((int32_t)text[k] + 3);
        }
        i = j;
    }

    // greedy best-score adjacent merge
    std::string merged;
    while (true) {
        float best_score = -1e10f;
        int32_t best_id = -1;
        int64_t best_idx = -1;
        for (int64_t k = 0; k + 1 < (int64_t)tokens.size(); k++) {
            merged = b->vocab[tokens[k]];
            merged += b->vocab[tokens[k + 1]];
            auto it = b->index.find(merged);
            if (it != b->index.end() && b->scores[it->second] > best_score) {
                best_score = b->scores[it->second];
                best_id = it->second;
                best_idx = k;
            }
        }
        if (best_idx < 0) break;
        tokens[best_idx] = best_id;
        tokens.erase(tokens.begin() + best_idx + 1);
    }

    std::memcpy(out, tokens.data(), tokens.size() * sizeof(int32_t));
    return (int32_t)tokens.size();
}

}  // extern "C"
